"""Householder QR, compact-WY application, TSQR, least squares.

Reference: Elemental ``src/lapack_like/factor/QR.cpp`` +
``QR/{Householder,PanelHouseholder,TS,ApplyQ,SolveAfter}.hpp`` and
``src/lapack_like/reflect/ApplyPacked`` -- BASELINE.json's
"Householder QR / least-squares (TSQR panel factor)" config.

TPU-first design (same pattern as lu.py): the panel is gathered to
[STAR,STAR] and reduced REDUNDANTLY on every device with a local larfg
fori_loop (the reference's ``qr::PanelHouseholder`` runs one Nrm2 AllReduce
per column).  The trailing update is the compact-WY form
``A2 -= V T^H (V^H A2)`` where ``V^H A2`` is a storage matmul whose
mc-sharded contraction GSPMD lowers to local MXU product + psum -- exactly
the reference's [MC,STAR]/[STAR,MR] Her2k-style update, with T computed
locally (larft) on the replicated panel.

Packing follows LAPACK geqrf: R on/above the diagonal, the Householder
vectors' tails below it (unit diagonal implicit), plus a tau vector.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax

from ..core.dist import MC, MR, VC, STAR
from ..core.distmatrix import DistMatrix
from ..core.view import view, update_view
from ..redist.engine import redistribute
from ..blas.level3 import _blocksize, _check_mcmr, trsm
from .lu import _update_cols_lt, _update_cols_ge


# ---------------------------------------------------------------------
# replicated panel reduction (larfg loop) + larft
# ---------------------------------------------------------------------

def _panel_qr(P):
    """Unblocked Householder QR of a replicated (M, k) panel.

    Returns (packed V\\R panel, tau).  LAPACK larfg conventions: real beta,
    H_j = I - tau_j v_j v_j^H, applied as H^H during the reduction, so the
    panel ends as Q^H A with Q = H_0 ... H_{k-1}."""
    M, k = P.shape
    ridx = jnp.arange(M)
    cidx = jnp.arange(k)

    def body(j, state):
        P, tau = state
        col = P[:, j]
        alpha = col[j]
        tail = jnp.where(ridx > j, col, 0)
        sigma = jnp.sum(jnp.abs(tail) ** 2)
        anorm = jnp.sqrt(jnp.abs(alpha) ** 2 + sigma)
        re_a = jnp.real(alpha)
        beta = -jnp.sign(jnp.where(re_a == 0, 1.0, re_a)) * anorm   # real
        degenerate = anorm == 0
        safe_beta = jnp.where(degenerate, 1.0, beta)
        tau_j = jnp.where(degenerate, 0.0, (safe_beta - alpha) / safe_beta)
        denom = alpha - safe_beta
        safe_denom = jnp.where(denom == 0, 1.0, denom)
        v = jnp.where(ridx > j, col / safe_denom, 0)
        v = v.at[j].set(jnp.where(degenerate, 0.0, 1.0).astype(P.dtype))
        # apply H_j^H = I - conj(tau) v v^H to the trailing columns.
        # HIGHEST precision: on TPU the default lowers dots to bf16, which
        # would corrupt the reflectors themselves (panel work is tiny).
        w = jnp.matmul(jnp.conj(v), P, precision=lax.Precision.HIGHEST)
        upd = jnp.outer(jnp.conj(tau_j) * v, w)
        P = P - jnp.where(cidx[None, :] > j, upd, 0)
        # store [beta; v-tail] in column j
        newcol = jnp.where(ridx > j, v, P[:, j]).at[j].set(
            jnp.asarray(beta, P.dtype))
        newcol = jnp.where(ridx >= j, newcol, P[:, j])
        P = P.at[:, j].set(newcol)
        tau = tau.at[j].set(jnp.asarray(tau_j, tau.dtype))
        return P, tau

    tau0 = jnp.zeros((k,), P.dtype)
    return lax.fori_loop(0, k, body, (P, tau0))


def _larft(V, tau):
    """Forward-columnwise block-reflector triangle: Q = I - V T V^H."""
    k = tau.shape[0]
    B = jnp.matmul(jnp.conj(V).T, V, precision=lax.Precision.HIGHEST)
    kidx = jnp.arange(k)

    def body(i, T):
        col = jnp.where(kidx < i, B[:, i], 0)
        newcol = -tau[i] * jnp.matmul(T, col, precision=lax.Precision.HIGHEST)
        newcol = newcol.at[i].set(tau[i])
        return T.at[:, i].set(newcol)

    return lax.fori_loop(0, k, body, jnp.zeros((k, k), V.dtype))


def _panel_v(Pf):
    """Unit-lower V from a packed panel (replicated)."""
    M, k = Pf.shape
    return jnp.tril(Pf, -1) + jnp.eye(M, k, dtype=Pf.dtype)


# ---------------------------------------------------------------------
# blocked Householder QR
# ---------------------------------------------------------------------

def qr(A: DistMatrix, nb: int | None = None, precision=None):
    """Blocked Householder QR; returns (packed, tau) in geqrf format."""
    _check_mcmr(A)
    m, n = A.gshape
    g = A.grid
    r, c = g.height, g.width
    ib = _blocksize(nb, math.lcm(r, c), min(m, n))
    kend = min(m, n)
    taus = []
    for s in range(0, kend, ib):
        e = min(s + ib, kend)
        nbw = e - s
        e_up = min(-(-e // c) * c, n)
        panel = redistribute(view(A, rows=(s, m), cols=(s, e_up)), STAR, STAR)
        Pf, tau = _panel_qr(panel.local[:, :nbw])
        taus.append(tau)
        if e_up > e:
            Pf_w = jnp.pad(Pf, ((0, 0), (0, e_up - e)))
        else:
            Pf_w = Pf
        Pf_ss = DistMatrix(Pf_w, (m - s, e_up - s), STAR, STAR, 0, 0, g)
        A = _update_cols_lt(A, redistribute(Pf_ss, MC, MR), (s, m), (s, e_up), e)
        if e < n:
            V = _panel_v(Pf)
            T = _larft(V, tau)
            V_ss = DistMatrix(V, (m - s, nbw), STAR, STAR, 0, 0, g)
            V_mc = redistribute(V_ss, MC, STAR)
            A2 = view(A, rows=(s, m), cols=(s, n))
            W = jnp.matmul(jnp.conj(V_mc.local).T, A2.local,
                           precision=precision)          # [STAR,MR] storage
            W = jnp.matmul(jnp.conj(T).T, W, precision=precision)
            upd = jnp.matmul(V_mc.local, W, precision=precision)
            A = _update_cols_ge(A, A2.with_local(A2.local - upd.astype(A.dtype)),
                                (s, m), (s, n), e)
    return A, jnp.concatenate(taus) if taus else jnp.zeros((0,), A.dtype)


def apply_q(Ap: DistMatrix, tau, B: DistMatrix, orient: str = "N",
            nb: int | None = None, precision=None) -> DistMatrix:
    """B := Q B ('N') or Q^H B ('C'), Q from (packed, tau)
    (``qr::ApplyQ`` / ``ApplyPackedReflectors``).  ``nb`` must match the
    factorization's blocking (same default derivation)."""
    _check_mcmr(Ap, B)
    m, n = Ap.gshape
    if B.gshape[0] != m:
        raise ValueError(f"B height {B.gshape[0]} != {m}")
    g = Ap.grid
    r, c = g.height, g.width
    ib = _blocksize(nb, math.lcm(r, c), min(m, n))
    kend = min(m, n)
    starts = list(range(0, kend, ib))
    if orient == "N":
        starts = starts[::-1]
    for s in starts:
        e = min(s + ib, kend)
        nbw = e - s
        e_up = min(-(-e // c) * c, n)
        panel = redistribute(view(Ap, rows=(s, m), cols=(s, e_up)), STAR, STAR)
        V = _panel_v(panel.local[:, :nbw])
        T = _larft(V, tau[s:e])
        Tm = jnp.conj(T).T if orient == "C" else T
        V_ss = DistMatrix(V, (m - s, nbw), STAR, STAR, 0, 0, g)
        V_mc = redistribute(V_ss, MC, STAR)
        B2 = view(B, rows=(s, m))
        W = jnp.matmul(jnp.conj(V_mc.local).T, B2.local, precision=precision)
        W = jnp.matmul(Tm, W, precision=precision)
        upd = jnp.matmul(V_mc.local, W, precision=precision)
        B = update_view(B, B2.with_local(B2.local - upd.astype(B.dtype)),
                        rows=(s, m))
    return B


def explicit_q(Ap: DistMatrix, tau, nb: int | None = None,
               precision=None) -> DistMatrix:
    """The m x m unitary Q as a DistMatrix (``qr::ExplicitUnitary``)."""
    from ..matrices.basic import identity
    I = identity(Ap.gshape[0], grid=Ap.grid, dtype=Ap.dtype)
    return apply_q(Ap, tau, I, orient="N", nb=nb, precision=precision)


def least_squares(A: DistMatrix, B: DistMatrix, nb: int | None = None,
                  precision=None) -> DistMatrix:
    """Minimize ||A X - B||_F for m >= n via QR (``El::LeastSquares``,
    dense path of ``src/lapack_like/euclidean_min/LeastSquares.cpp``).

    Fully distributed: Q^H B via packed reflectors, then a distributed
    triangular solve against the interior-extracted R (no replication)."""
    from ..redist.interior import interior_view      # qr <- interior is cycle-free
    from ..blas.level1 import make_trapezoidal
    _check_mcmr(A, B)
    m, n = A.gshape
    if m < n:
        raise ValueError("least_squares requires m >= n (tall)")
    Ap, tau = qr(A, nb=nb, precision=precision)
    Y = apply_q(Ap, tau, B, orient="C", nb=nb, precision=precision)
    R = make_trapezoidal(interior_view(Ap, (0, n), (0, n)), "U")
    Y1 = interior_view(Y, (0, n), (0, B.gshape[1]))
    return trsm("L", "U", "N", R, Y1, nb=nb, precision=precision)


# ---------------------------------------------------------------------
# TSQR (tall-skinny)
# ---------------------------------------------------------------------

def tsqr(A: DistMatrix):
    """Tall-skinny QR of a [VC,STAR] matrix (``qr::TS``): per-device local
    QR + one all-gather of the p small R factors + a redundant stacked QR.
    Returns (Q [VC,STAR] with orthonormal columns, R [STAR,STAR])."""
    if A.dist != (VC, STAR) or (A.calign, A.ralign) != (0, 0):
        raise ValueError(f"tsqr expects zero-aligned [VC,STAR], got {A}")
    m, k = A.gshape
    g = A.grid
    r, c = g.height, g.width
    p = r * c
    if m < k:
        raise ValueError("tsqr needs m >= k")

    import jax
    from jax.sharding import PartitionSpec as P

    def f(a):
        q1, r1 = jnp.linalg.qr(a, mode="reduced")        # (lr,kk),(kk,k)
        rs = lax.all_gather(r1, ("mr", "mc"), axis=0)    # VC rank order
        kk = r1.shape[0]
        stacked = rs.reshape(p * kk, k)
        q2, R = jnp.linalg.qr(stacked, mode="reduced")   # (p*kk,k),(k,k)
        vc = lax.axis_index("mc") + r * lax.axis_index("mr")
        q2b = lax.dynamic_slice_in_dim(q2, vc * kk, kk, axis=0)
        return q1 @ q2b, R

    # float32-accurate dots: the TPU default would run the local QRs' and the
    # Q1*Q2 product's matmuls in bf16
    with jax.default_matmul_precision("highest"):
        Qs, Rs = jax.shard_map(
            f, mesh=g.mesh, in_specs=(A.spec,),
            out_specs=(A.spec, P(None, None)), check_vma=False,
        )(A.local)
    Q = DistMatrix(Qs, (m, k), VC, STAR, 0, 0, g)
    R = DistMatrix(Rs, (k, k), STAR, STAR, 0, 0, g)
    return Q, R
