"""Euclidean minimization breadth: Ridge, Tikhonov, GLM, LSE.

Reference: Elemental ``src/lapack_like/euclidean_min/`` --
``Ridge.cpp`` (``El::Ridge``), ``Tikhonov.cpp``, ``GLM.cpp`` (general
Gauss-Markov linear model), ``LSE.cpp`` (equality-constrained least
squares).  The dense ``LeastSquares`` driver lives in :mod:`.qr`.

TPU-native shapes: Ridge/Tikhonov ride the stacked-QR formulation (one
``vstack`` + the distributed least-squares path -- numerically safer than
normal equations); LSE solves the symmetric-indefinite KKT system with the
Bunch-Kaufman LDL; GLM uses the covariance-form elimination with Cholesky
solves (requires B of full row rank).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.distmatrix import DistMatrix
from ..redist.engine import redistribute, transpose_dist
from ..redist.interior import interior_view, interior_update, vstack, _blank
from ..core.dist import MC, MR
from ..blas.level1 import shift_diagonal
from ..blas.level3 import _check_mcmr, gemm
from .qr import least_squares
from .cholesky import cholesky, cholesky_solve_after
from .ldl import ldl, ldl_solve_after


def ridge(A: DistMatrix, b: DistMatrix, gamma: float,
          nb: int | None = None, precision=None) -> DistMatrix:
    """min ||A x - b||^2 + gamma^2 ||x||^2 (``El::Ridge``): the stacked
    least-squares problem [A; gamma I] x = [b; 0]."""
    _check_mcmr(A, b)
    m, n = A.gshape
    gI = shift_diagonal(_blank(n, n, A), gamma)
    As = vstack(A, gI)
    bs = vstack(b, _blank(n, b.gshape[1], b))
    return least_squares(As, bs, nb=nb, precision=precision)


def tikhonov(A: DistMatrix, b: DistMatrix, G: DistMatrix,
             nb: int | None = None, precision=None) -> DistMatrix:
    """min ||A x - b||^2 + ||G x||^2 (``El::Tikhonov``): stacked
    least squares [A; G] x = [b; 0]."""
    _check_mcmr(A, b, G)
    As = vstack(A, G)
    bs = vstack(b, _blank(G.gshape[0], b.gshape[1], b))
    return least_squares(As, bs, nb=nb, precision=precision)


def lse(A: DistMatrix, b: DistMatrix, C: DistMatrix, d: DistMatrix,
        nb: int | None = None, precision=None):
    """Equality-constrained least squares min ||A x - b|| s.t. C x = d
    (``El::LSE``): the symmetric-indefinite KKT system

        [ A^H A   C^H ] [ x      ]   [ A^H b ]
        [   C      0  ] [ lambda ] = [   d   ]

    solved with the pivoted LDL.  Returns x."""
    _check_mcmr(A, b, C, d)
    m, n = A.gshape
    p = C.gshape[0]
    K = _blank(n + p, n + p, A)
    K = interior_update(K, gemm(A, A, orient_a="C", nb=nb,
                                precision=precision), (0, 0))
    K = interior_update(K, _tp_conj(C), (0, n))
    K = interior_update(K, C, (n, 0))
    rhs = vstack(gemm(A, b, orient_a="C", nb=nb, precision=precision), d)
    conj = bool(jnp.issubdtype(A.dtype, jnp.complexfloating))
    Lp, dk, ek, perm = ldl(K, conjugate=conj, nb=nb, precision=precision)
    sol = ldl_solve_after(Lp, dk, ek, perm, rhs, conjugate=conj, nb=nb,
                          precision=precision)
    return interior_view(sol, (0, n), (0, b.gshape[1]))


def glm(A: DistMatrix, B: DistMatrix, d: DistMatrix,
        nb: int | None = None, precision=None):
    """General (Gauss-Markov) linear model (``El::GLM``):

        min ||y||  s.t.  d = A x + B y

    via the covariance form with W = B B^H HPD (B full row rank):
    solve (A^H W^{-1} A) x = A^H W^{-1} d, then y = B^H W^{-1} (d - A x).
    Returns (x, y)."""
    _check_mcmr(A, B, d)
    m, n = A.gshape
    Bt = _tp_conj(B)
    W = gemm(B, B, orient_b="C", nb=nb, precision=precision)
    Lw = cholesky(W, "L", nb=nb, precision=precision)
    Wid = cholesky_solve_after(Lw, d, nb=nb, precision=precision)
    WiA = cholesky_solve_after(Lw, A, nb=nb, precision=precision)
    M = gemm(_tp_conj(A), WiA, nb=nb, precision=precision)
    rhs = gemm(_tp_conj(A), Wid, nb=nb, precision=precision)
    # M = A^H W^{-1} A is HPD for full-column-rank A
    Lm = cholesky(M, "L", nb=nb, precision=precision)
    x = cholesky_solve_after(Lm, rhs, nb=nb, precision=precision)
    resid = d.with_local(d.local - gemm(A, x, nb=nb, precision=precision).local)
    y = gemm(Bt, cholesky_solve_after(Lw, resid, nb=nb, precision=precision),
             nb=nb, precision=precision)
    return x, y


def _tp_conj(A):
    return redistribute(transpose_dist(A, conj=True), MC, MR)
