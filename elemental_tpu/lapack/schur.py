"""Schur decomposition (spectral divide-and-conquer), triangular
eigenvectors, general eigensolver, and pseudospectra.

Reference: Elemental ``src/lapack_like/spectral/Schur.cpp`` +
``Schur/SDC.hpp`` (``El::schur::SDC``: matrix-sign spectral divide and
conquer with randomized splitting lines), ``TriangEig.cpp``
(``El::TriangEig`` via ``MultiShiftTrsm``), ``Eig.cpp``, and
``Pseudospectra.cpp`` (``El::pspec``: batched inverse-iteration maps over a
shift window).

TPU-native notes:
  * The SDC split is the sign-function analog of funcs._dc_eig: one scaled
    Newton ``sign`` (LU solves -- MXU-shaped) per level, randomized
    range-finder + packed-reflector rotation, interior extract/embed at the
    data-dependent split.  Splitting lines are retried over rotations
    (vertical / horizontal / random angle) like the reference's randomized
    Mobius sweeps.
  * The base case gathers the block and runs the sequential QR algorithm
    redundantly -- EXACTLY the reference's upstream behavior (its
    distributed Schur defers to sequential LAPACK ``hseqr``; SURVEY §3.4).
  * ``triang_eig`` batches all n shifted back-substitutions into one
    multishift sweep where rows >= j of column j's system are replaced by
    identity rows -- the singular shifts (T_jj = lambda_j) never divide.
  * ``pseudospectra`` runs inverse power iteration on (T - z I) for the
    whole shift grid at once through ``multishift_trsm``.

Output convention: COMPLEX Schur form (real input is cast), A = Q T Q^H
with T upper triangular.

Backend note: the device-side arithmetic here is complex64/128; XLA:TPU
supports complex dots via real decomposition, but experimental tunneled
backends may not (the axon plugin raises UNIMPLEMENTED) -- validate on the
host-CPU mesh there.  Real-input control solvers (Sylvester/Lyapunov/
Riccati) stay in real arithmetic and are unaffected.
"""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ..core.dist import MC, MR, STAR
from ..core.distmatrix import DistMatrix, from_global, to_global
from ..redist.engine import redistribute, transpose_dist
from ..redist.interior import interior_view, interior_update, _blank
from ..blas.level1 import (get_diagonal, shift_diagonal, frobenius_norm,
                           make_trapezoidal, diagonal_scale, _global_indices)
from ..blas.level3 import _check_mcmr, _blocksize, gemm
from .lu import _hi
from .funcs import sign as _matrix_sign
from .qr import qr, apply_q


def _complex_dtype(dtype):
    return jnp.result_type(dtype, jnp.complex64)


def _replicated_schur(A: DistMatrix):
    """Base case: gather + sequential complex QR algorithm, run on host
    (the reference's redundant-hseqr fallback)."""
    try:
        import scipy.linalg
    except ImportError as exc:                      # pragma: no cover
        raise ImportError(
            "schur/eig/pseudospectra need scipy for the sequential "
            "QR-algorithm base case (the reference's hseqr analog); "
            "install scipy or raise `base` is not an option -- every "
            "recursion bottoms out here") from exc
    n = A.gshape[0]
    Ag = np.asarray(to_global(A))
    T, Q = scipy.linalg.schur(Ag, output="complex")
    g = A.grid
    Td = redistribute(DistMatrix(jnp.asarray(T, A.dtype), (n, n), STAR, STAR,
                                 0, 0, g), MC, MR)
    Qd = redistribute(DistMatrix(jnp.asarray(Q, A.dtype), (n, n), STAR, STAR,
                                 0, 0, g), MC, MR)
    return Td, Qd


def _sdc(A: DistMatrix, base: int, nb, precision, seed: int, depth: int = 0):
    """Recursive sign-function SDC; returns (T, Q) with A = Q T Q^H."""
    n = A.gshape[0]
    g = A.grid
    if n <= max(base, 2) or depth > 60:
        return _replicated_schur(A)
    d = get_diagonal(A).local[:, 0]
    rng = np.random.default_rng(0x5DC0 + 31 * seed + depth)
    scale = max(float(frobenius_norm(A)), 1e-30)
    # candidate splitting lines: (shift sigma, rotation theta); the sign of
    # e^{-i theta}(A - sigma I) splits the spectrum across the line through
    # sigma with direction theta + pi/2
    cands = [(complex(float(jnp.median(jnp.real(d)))), 0.0),
             (1j * float(jnp.median(jnp.imag(d))), math.pi / 2)]
    for _ in range(3):
        c = complex(d[rng.integers(n)]) + \
            (rng.normal() + 1j * rng.normal()) * 0.1 * scale / math.sqrt(n)
        cands.append((c, rng.uniform(0, math.pi)))
    split = None
    for sigma, theta in cands:
        try:
            As = shift_diagonal(A, -jnp.asarray(sigma, A.dtype))
            phase = jnp.asarray(np.exp(-1j * theta), A.dtype)
            S = _matrix_sign(As.with_local(phase * As.local), nb=nb,
                             precision=_hi(precision))
        except FloatingPointError:
            continue
        P = shift_diagonal(S.with_local(-0.5 * S.local), 0.5)
        kf = float(jnp.real(jnp.sum(jnp.where(_diag_mask(P), P.local, 0))))
        if not math.isfinite(kf):
            continue        # sign silently filled with NaN/Inf: next line
        k = int(round(kf))
        if not (0 < k < n):
            continue
        G = rng.normal(size=(n, k)) + 1j * rng.normal(size=(n, k))
        Gd = from_global(G.astype(np.dtype(A.dtype)), MC, MR, grid=g)
        Y = gemm(P, Gd, nb=nb, precision=_hi(precision))
        Qp, tau = qr(Y, nb=nb, precision=_hi(precision))
        T1_ = apply_q(Qp, tau, A, orient="C", nb=nb, precision=_hi(precision))
        T2_ = redistribute(transpose_dist(T1_, conj=True), MC, MR)
        T3_ = apply_q(Qp, tau, T2_, orient="C", nb=nb, precision=_hi(precision))
        C = redistribute(transpose_dist(T3_, conj=True), MC, MR)
        # accept only a numerically clean split: the rotated (2,1) block
        # must be negligible (an unconverged sign near the line leaves mass
        # there; the reference's SDC performs the same residual gate)
        A21 = interior_view(C, (k, n), (0, k))
        if float(frobenius_norm(A21)) > 1e-6 * scale:
            continue
        split = (k, Qp, tau, C)
        break
    if split is None:
        return _replicated_schur(A)
    k, Qp, tau, C = split
    A11 = interior_view(C, (0, k), (0, k))
    A22 = interior_view(C, (k, n), (k, n))
    C12 = interior_view(C, (0, k), (k, n))
    Ta, Qa = _sdc(A11, base, nb, precision, 2 * seed + 1, depth + 1)
    Tb, Qb = _sdc(A22, base, nb, precision, 2 * seed + 2, depth + 1)
    T12 = gemm(gemm(Qa, C12, orient_a="C", nb=nb, precision=_hi(precision)), Qb,
               nb=nb, precision=_hi(precision))
    T = _blank(n, n, A)
    T = interior_update(T, Ta, (0, 0))
    T = interior_update(T, T12, (0, k))
    T = interior_update(T, Tb, (k, k))
    BD = _blank(n, n, A)
    BD = interior_update(BD, Qa, (0, 0))
    BD = interior_update(BD, Qb, (k, k))
    Q = apply_q(Qp, tau, BD, orient="N", nb=nb, precision=_hi(precision))
    return make_trapezoidal(T, "U"), Q


def _diag_mask(A: DistMatrix):
    I, J = _global_indices(A)
    return (J[None, :] == I[:, None]) & (I[:, None] < A.gshape[0])


def _global_colnorms(X: DistMatrix, k: int):
    """Column 2-norms in GLOBAL order from the storage array.  Out-of-range
    (padding) storage columns are DROPPED -- clipping first would clobber
    column k-1."""
    ns = jnp.sqrt(jnp.sum(jnp.abs(X.local) ** 2, axis=0))
    _, J = _global_indices(X)
    return jnp.zeros((k,), ns.dtype).at[J].set(ns, mode="drop")


def schur(A: DistMatrix, base: int | None = None, nb: int | None = None,
          precision=None):
    """Complex Schur decomposition A = Q T Q^H (``El::Schur``; SDC path for
    blocks above ``base``).  Returns (T upper triangular, Q unitary)."""
    _check_mcmr(A)
    n = A.gshape[0]
    if A.gshape != (n, n):
        raise ValueError(f"schur needs square, got {A.gshape}")
    cdtype = _complex_dtype(A.dtype)
    Ac = A.astype(cdtype)
    return _sdc(Ac, base if base is not None else 128, nb, precision, seed=1)


def triang_eig(T: DistMatrix, nb: int | None = None, precision=None):
    """Eigenvectors of an upper-triangular T (``El::TriangEig``): one
    batched :func:`multishift_trsm` backward sweep whose diagonal blocks
    are modified per column -- rows >= j become identity rows (so the
    singular shift T_jj - lambda_j never divides) and near-zero pivots are
    clamped to ~eps ||T|| (LAPACK trevc's smin perturbation for repeated /
    defective eigenvalues).  Returns (w = diag(T), V) with unit 2-norm
    columns."""
    from ..blas.level3 import multishift_trsm
    from ..blas.level1 import max_norm
    _check_mcmr(T)
    n = T.gshape[0]
    g = T.grid
    w = get_diagonal(T).local[:, 0]
    rdtype = jnp.zeros((), T.dtype).real.dtype
    smin = jnp.finfo(rdtype).eps * jnp.maximum(max_norm(T), 1e-300) \
        + jnp.finfo(rdtype).tiny

    def hook(M, sg, jg, rowg):
        eye = jnp.eye(M.shape[0], dtype=M.dtype)
        M = jnp.where((rowg >= jg)[:, None], eye, M)
        d_ = jnp.diagonal(M)
        mag = jnp.abs(d_)
        dc = jnp.where(mag < smin,
                       jnp.where(mag == 0, smin,
                                 d_ * (smin / jnp.where(mag == 0, 1, mag))),
                       d_)
        return M + jnp.diag((dc - d_))

    # RHS: e_j per column -- the modified system keeps column j's coupling
    # T[i, j] x[j], so rows i < j see exactly (T - lambda_j)[:j,:j] x = -T[:j, j]
    B = shift_diagonal(_blank(n, n, T), 1)
    X = multishift_trsm("U", "N", T, w, B, nb=nb, precision=_hi(precision),
                        diag_hook=hook)
    # normalize columns to unit 2-norm
    norms = _global_colnorms(X, n)
    inv = jnp.where(norms > 0, 1.0 / jnp.where(norms == 0, 1, norms), 0)
    dinv = DistMatrix(inv[:, None].astype(X.dtype), (n, 1), STAR, STAR, 0, 0, g)
    return w, diagonal_scale("R", dinv, X)


def eig(A: DistMatrix, base: int | None = None, nb: int | None = None,
        precision=None):
    """General (non-Hermitian) eigendecomposition via Schur + TriangEig
    (``El::Eig``): returns (w, V) with A V ~= V diag(w), unit columns."""
    T, Q = schur(A, base=base, nb=nb, precision=_hi(precision))
    w, Vt = triang_eig(T, nb=nb, precision=_hi(precision))
    V = gemm(Q, Vt, nb=nb, precision=_hi(precision))
    # re-normalize (Q is unitary so norms are preserved up to rounding)
    return w, V


def pseudospectra(A: DistMatrix, re_window, im_window, nx: int = 20,
                  ny: int = 20, iters: int = 30, triangular: bool = False,
                  base: int | None = None, nb: int | None = None,
                  precision=None, seed: int = 0, tol: float = 1e-3,
                  check_every: int = 3, deflate: bool = True,
                  quiet_checks: int = 3, snapshot=None):
    """Inverse-norm map est. sigma_min(A - z I) over a 2-D shift window
    (``El::Pseudospectra``): Schur once, then batched inverse power
    iteration on (T - z I)^H (T - z I) through ``multishift_trsm``.

    Deflation (the ``Pseudospectra/{Power,Lanczos}.hpp`` machinery): every
    ``check_every`` sweeps, shifts whose estimate moved by less than
    ``tol`` relatively for ``quiet_checks`` CONSECUTIVE checks are FROZEN
    and removed from the batch (inverse iteration can plateau for a few
    sweeps before converging toward a different value, so a single quiet
    check is not convergence; any loud check resets the shift's counter);
    the active set repacks to the next power-of-two width, so XLA compiles
    at most log2(k) shapes while converged shifts stop costing solves.  The
    ``snapshot`` callable (``SnapshotCtrl`` analog) receives
    ``(sweep, Z, sigmin_so_far)`` after every check for progressive dumps.

    Returns (Z grid (ny, nx) complex, sigmin (ny, nx) float) as host numpy.
    """
    from ..blas.level3 import multishift_trsm
    from ..redist.interior import interior_view
    from .lu import permute_cols
    _check_mcmr(A)
    n = A.gshape[0]
    g = A.grid
    if triangular:
        T = A.astype(_complex_dtype(A.dtype))
    else:
        T, _Q = schur(A, base=base, nb=nb, precision=_hi(precision))
    xs = np.linspace(re_window[0], re_window[1], nx)
    ys = np.linspace(im_window[0], im_window[1], ny)
    Z = xs[None, :] + 1j * ys[:, None]
    all_shifts = Z.reshape(-1)
    k = all_shifts.shape[0]
    rng = np.random.default_rng(seed)
    V0 = rng.normal(size=(n, k)) + 1j * rng.normal(size=(n, k))
    V0 /= np.linalg.norm(V0, axis=0, keepdims=True)
    V = from_global(V0.astype(np.dtype(T.dtype)), MC, MR, grid=g)

    active = np.arange(k)           # global ids of live columns
    ka = k                          # current (padded) batch width
    sh_act = all_shifts.copy()      # length ka, padded with repeats
    est_final = np.zeros(k)
    prev = np.full(k, np.inf)
    quiet = np.zeros(k, dtype=int)      # consecutive quiet checks per shift
    need = max(int(quiet_checks), 1)
    sweep = 0

    def one_sweep(V, shifts_dev, cshifts_dev, width):
        Y = multishift_trsm("U", "N", T, shifts_dev, V, nb=nb,
                            precision=_hi(precision))
        ny_ = _global_colnorms(Y, width)
        dinv = DistMatrix(jnp.where(ny_ > 0, 1 / jnp.where(ny_ == 0, 1, ny_),
                                    0)[:, None].astype(T.dtype),
                          (width, 1), STAR, STAR, 0, 0, g)
        Yn = diagonal_scale("R", dinv, Y)
        U = multishift_trsm("U", "C", T, cshifts_dev, Yn, nb=nb,
                            precision=_hi(precision))
        nu = _global_colnorms(U, width)
        est = jnp.sqrt(ny_ * nu)
        dinv2 = DistMatrix(jnp.where(nu > 0, 1 / jnp.where(nu == 0, 1, nu),
                                     0)[:, None].astype(T.dtype),
                           (width, 1), STAR, STAR, 0, 0, g)
        return diagonal_scale("R", dinv2, U), est

    while sweep < iters and active.size:
        shifts_dev = jnp.asarray(sh_act, T.dtype)
        cshifts_dev = jnp.conj(shifts_dev)
        est = None
        for _ in range(min(check_every, iters - sweep)):
            V, est = one_sweep(V, shifts_dev, cshifts_dev, ka)
            sweep += 1
        estn = np.asarray(est)[: active.size]
        est_final[active] = estn
        rel = np.abs(estn - prev[active]) / np.maximum(np.abs(estn), 1e-300)
        prev[active] = estn
        quiet[active] = np.where(rel < tol, quiet[active] + 1, 0)
        conv = quiet[active] >= need
        if snapshot is not None:
            part = np.where(np.isfinite(est_final) & (est_final > 0),
                            1.0 / np.maximum(est_final, 1e-300), 0.0)
            snapshot(sweep, Z, part.reshape(ny, nx))
        if not (deflate and conv.any()) or sweep >= iters:
            if conv.all():
                break
            continue
        keep = np.nonzero(~conv)[0]
        if keep.size == 0:
            break
        active = active[keep]
        # repack live columns first, pad to the next power of two -- but
        # never GROW the batch (next_pow2(keep) can exceed a non-pow2 ka)
        ka2 = min(ka, 1 << max(int(np.ceil(np.log2(max(keep.size, 1)))), 0))
        pad_ids = np.concatenate(
            [keep, np.repeat(keep[:1], ka2 - keep.size)]) \
            if ka2 > keep.size else keep
        Vp = permute_cols(V, jnp.asarray(
            np.concatenate([pad_ids, np.setdiff1d(np.arange(ka), pad_ids)])
            [:ka]))
        V = interior_view(Vp, (0, n), (0, ka2)) if ka2 < ka else Vp
        sh_act = sh_act[pad_ids]
        ka = ka2
    estn = est_final
    # exactly-singular shifts drive the solves to inf/0: sigma_min = 0 there
    sigmin = np.where(np.isfinite(estn) & (estn > 0), 1.0 / np.maximum(
        estn, 1e-300), 0.0)
    return Z, sigmin.reshape(ny, nx)
