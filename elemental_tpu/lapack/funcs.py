"""Matrix functions: QDWH polar, matrix sign, inverses, pseudoinverse,
square roots, and the polar-based spectral divide-and-conquer eigensolver.

Reference: Elemental ``src/lapack_like/funcs/`` -- ``Sign.cpp`` (``El::Sign``,
Newton iteration with scaling), ``Polar`` (``polar::QDWH``),
``Inverse/**`` (``El::Inverse`` via LU, ``TriangularInverse``,
``HPDInverse``), ``Pseudoinverse.cpp``, ``SquareRoot.cpp`` (Newton).

TPU-native design (SURVEY.md §8.1 item 4, PAPERS.md arXiv 2112.09017): the
QDWH iteration is the workhorse -- every step is a Cholesky or QR plus a few
large matmuls, i.e. pure MXU food -- and it REPLACES the reference's
bundled PMRRR: :func:`_qdwh_eig` splits the spectrum recursively with polar
projectors, extracting the deflated blocks at data-dependent offsets with
:mod:`..redist.interior` (one ppermute per dim -- no replicated construct
anywhere, unlike the tridiagonal fallback path in :mod:`.spectral`).

The scalar QDWH parameter recurrence (a, b, c, l) is data-INdependent given
the initial lower bound, so it is precomputed on the host and the iteration
count is static per (alpha, l0) -- jit-friendly, no data-dependent control
flow on device.
"""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ..core.dist import MC, MR, STAR
from ..core.distmatrix import DistMatrix
from ..redist.engine import redistribute, transpose_dist
from ..redist.interior import interior_view, interior_update, vstack, _blank
from ..blas.level1 import (frobenius_norm, one_norm, infinity_norm,
                           shift_diagonal, get_diagonal, make_symmetric,
                           trace as dm_trace)
from ..blas.level3 import _check_mcmr, gemm, trsm, herk
from .cholesky import cholesky, hpd_solve
from .lu import lu_solve, _hi
from .qr import qr, apply_q


def _real_dtype(dtype):
    return jnp.zeros((), dtype).real.dtype


def _eps_of(dtype) -> float:
    return float(jnp.finfo(_real_dtype(dtype)).eps)


def _identity_like(A: DistMatrix, m: int, n: int | None = None) -> DistMatrix:
    out = _blank(m, n or m, A)
    return shift_diagonal(out, 1)


def _hermitianize(H: DistMatrix) -> DistMatrix:
    Ht = redistribute(transpose_dist(H, conj=True), MC, MR)
    return H.with_local(0.5 * (H.local + Ht.local))


# ---------------------------------------------------------------------
# QDWH polar decomposition
# ---------------------------------------------------------------------

def _qdwh_schedule(l0: float, tol: float, maxiter: int = 32):
    """Host-side (a, b, c) parameter schedule from the lower bound l0.

    The dynamically-weighted Halley parameters (Nakatsukasa-Bai-Gygi /
    Nakatsukasa-Higham); l_{k+1} = l_k (a + b l^2) / (1 + c l^2) is
    data-independent, so the whole schedule is static."""
    params = []
    l = float(l0)
    while 1.0 - l > tol and len(params) < maxiter:
        l2 = l * l
        dd = (4.0 * (1.0 - l2) / (l2 * l2)) ** (1.0 / 3.0)
        sqd = math.sqrt(1.0 + dd)
        a = sqd + 0.5 * math.sqrt(
            max(8.0 - 4.0 * dd + 8.0 * (2.0 - l2) / (l2 * sqd), 0.0))
        b = (a - 1.0) ** 2 / 4.0
        c = a + b - 1.0
        params.append((a, b, c))
        l = l * (a + b * l2) / (1.0 + c * l2)
    # two pure-Halley cleanup steps (cubic convergence at the fixed point)
    params.append((3.0, 1.0, 3.0))
    params.append((3.0, 1.0, 3.0))
    return params


def _qdwh_step_chol(X: DistMatrix, a, b, c, nb, precision) -> DistMatrix:
    """Cholesky-variant step (safe once c is moderate): Z = I + c X^H X,
    Z = W W^H, X' = (b/c) X + (a - b/c) X W^{-H} W^{-1}."""
    n = X.gshape[1]
    Z = herk("L", X, alpha=c, orient="C", nb=nb, precision=_hi(precision))
    Z = shift_diagonal(Z, 1)
    W = cholesky(Z, "L", nb=nb, precision=_hi(precision))
    B = trsm("R", "L", "C", W, X, nb=nb, precision=_hi(precision))   # X W^{-H}
    B = trsm("R", "L", "N", W, B, nb=nb, precision=_hi(precision))   # ... W^{-1}
    return X.with_local((b / c) * X.local + (a - b / c) * B.local)


def _qdwh_step_qr(X: DistMatrix, a, b, c, nb, precision) -> DistMatrix:
    """QR-variant step (numerically safe for huge c):
    [sqrt(c) X; I] = Q R, X' = (b/c) X + (a - b/c)/sqrt(c) Q1 Q2^H."""
    m, n = X.gshape
    sc = math.sqrt(c)
    S = vstack(X.with_local(sc * X.local), _identity_like(X, n, n))
    Ap, tau = qr(S, nb=nb, precision=_hi(precision))
    # thin Q = Q [I; 0]
    E = _identity_like(X, m + n, n)
    Qthin = apply_q(Ap, tau, E, orient="N", nb=nb, precision=_hi(precision))
    Q1 = interior_view(Qthin, (0, m), (0, n))
    Q2 = interior_view(Qthin, (m, m + n), (0, n))
    G = gemm(Q1, Q2, orient_b="C", nb=nb, precision=_hi(precision))
    return X.with_local((b / c) * X.local + ((a - b / c) / sc) * G.local)


def polar(A: DistMatrix, nb: int | None = None, precision=None,
          l_min: float | None = None, qr_c_switch: float = 100.0):
    """Polar decomposition ``A = U H`` with U a partial isometry (m >= n:
    U^H U = I) and H Hermitian PSD (Elemental ``El::Polar``, QDWH variant).

    ``l_min``: lower bound on sigma_min(A)/sigma_max(A) (defaults to ~eps of
    the dtype -- an underestimate only adds iterations)."""
    _check_mcmr(A)
    m, n = A.gshape
    if m < n:
        # A^H = W K  =>  A = (W^H)(W K W^H)
        W, K = polar(redistribute(transpose_dist(A, conj=True), MC, MR),
                     nb=nb, precision=_hi(precision), l_min=l_min)
        U = redistribute(transpose_dist(W, conj=True), MC, MR)
        H = gemm(gemm(W, K, nb=nb, precision=_hi(precision)), W, orient_b="C",
                 nb=nb, precision=_hi(precision))
        return U, _hermitianize(H)

    alpha = float(jnp.sqrt(jnp.maximum(one_norm(A) * infinity_norm(A),
                                       jnp.finfo(_real_dtype(A.dtype)).tiny)))
    if not np.isfinite(alpha) or alpha == 0.0:
        return _identity_like(A, m, n), A.with_local(jnp.zeros_like(A.local))
    X = A.with_local((A.local / alpha).astype(A.dtype))
    eps = _eps_of(A.dtype)
    l0 = l_min if l_min is not None else eps
    for (a, b, c) in _qdwh_schedule(l0, tol=10 * eps):
        if c > qr_c_switch:
            X = _qdwh_step_qr(X, a, b, c, nb, precision)
        else:
            X = _qdwh_step_chol(X, a, b, c, nb, precision)
    U = X
    H = gemm(U, A, orient_a="C", nb=nb, precision=_hi(precision))
    return U, _hermitianize(H)


# ---------------------------------------------------------------------
# Matrix sign (Newton with norm scaling)
# ---------------------------------------------------------------------

def sign(A: DistMatrix, nb: int | None = None, precision=None,
         maxiter: int = 40, tol: float | None = None) -> DistMatrix:
    """Matrix sign function via scaled Newton iteration
    ``X <- (mu X + (mu X)^{-1}) / 2`` (``El::Sign``,
    ``src/lapack_like/funcs/Sign.cpp``; the Schur-SDC / Sylvester engine).

    Requires A to have no purely-imaginary eigenvalues (no eigenvalue on the
    unit... imaginary axis).  Host convergence loop over jitted device
    iterations (SURVEY.md §8.1 item 6)."""
    _check_mcmr(A)
    n = A.gshape[0]
    if A.gshape != (n, n):
        raise ValueError(f"sign needs square, got {A.gshape}")
    eps = _eps_of(A.dtype)
    tol = tol if tol is not None else n * 10 * eps
    X = A
    I = _identity_like(A, n)
    for it in range(maxiter):
        Xi = lu_solve(X, I, nb=nb, precision=_hi(precision))
        nx = float(frobenius_norm(X))
        ni = float(frobenius_norm(Xi))
        if not np.isfinite(nx) or not np.isfinite(ni):
            raise FloatingPointError("sign iteration diverged (singular A?)")
        mu = math.sqrt(ni / nx) if it < maxiter - 1 else 1.0
        Xnew = X.with_local(0.5 * (mu * X.local + (1.0 / mu) * Xi.local))
        delta = float(frobenius_norm(X.with_local(Xnew.local - X.local)))
        X = Xnew
        if delta <= tol * max(float(frobenius_norm(X)), 1e-30):
            break
    return X


# ---------------------------------------------------------------------
# Inverse family
# ---------------------------------------------------------------------

def inverse(A: DistMatrix, nb: int | None = None, precision=None) -> DistMatrix:
    """A^{-1} via LU with partial pivoting (``El::Inverse``,
    ``src/lapack_like/funcs/Inverse/General/``)."""
    _check_mcmr(A)
    n = A.gshape[0]
    if A.gshape != (n, n):
        raise ValueError(f"inverse needs square, got {A.gshape}")
    return lu_solve(A, _identity_like(A, n), nb=nb, precision=_hi(precision))


def triangular_inverse(uplo: str, A: DistMatrix, unit: bool = False,
                       nb: int | None = None, precision=None) -> DistMatrix:
    """inv(tri(A)) (``El::TriangularInverse``)."""
    _check_mcmr(A)
    n = A.gshape[0]
    return trsm("L", uplo, "N", A, _identity_like(A, n), unit=unit,
                nb=nb, precision=_hi(precision))


def hpd_inverse(A: DistMatrix, uplo: str = "L", nb: int | None = None,
                precision=None) -> DistMatrix:
    """Inverse of an HPD matrix via Cholesky (``El::HPDInverse``)."""
    _check_mcmr(A)
    n = A.gshape[0]
    return hpd_solve(A, _identity_like(A, n), uplo, nb=nb, precision=_hi(precision))


def pseudoinverse(A: DistMatrix, tol: float | None = None,
                  nb: int | None = None, precision=None) -> DistMatrix:
    """Moore-Penrose pseudoinverse via the SVD (``El::Pseudoinverse``):
    columns with s_i <= tol (default max(m,n) eps s_max) are dropped."""
    from ..blas.level1 import diagonal_scale
    from .spectral import svd
    m, n = A.gshape
    U, s, V = svd(A, vectors=True, nb=nb, precision=_hi(precision))
    smax = float(s[0]) if s.shape[0] else 0.0
    cut = tol if tol is not None else max(m, n) * _eps_of(A.dtype) * smax
    sinv = jnp.where(s > cut, 1.0 / jnp.where(s > cut, s, 1.0), 0.0)
    d = DistMatrix(sinv[:, None].astype(A.dtype), (s.shape[0], 1),
                   STAR, STAR, 0, 0, A.grid)
    Vs = diagonal_scale("R", d, V)
    return gemm(Vs, U, orient_b="C", nb=nb, precision=_hi(precision))


# ---------------------------------------------------------------------
# Square roots
# ---------------------------------------------------------------------

def square_root(A: DistMatrix, nb: int | None = None, precision=None,
                maxiter: int = 30, tol: float | None = None) -> DistMatrix:
    """Principal square root via the Denman-Beavers iteration
    (``El::SquareRoot`` uses the same Newton family):
    ``Y <- (Y + Z^{-1})/2, Z <- (Z + Y^{-1})/2``; Y -> A^{1/2}.

    Requires A to have no eigenvalues on the closed negative real axis."""
    _check_mcmr(A)
    n = A.gshape[0]
    if A.gshape != (n, n):
        raise ValueError(f"square_root needs square, got {A.gshape}")
    eps = _eps_of(A.dtype)
    tol = tol if tol is not None else n * 10 * eps
    I = _identity_like(A, n)
    Y, Z = A, I
    for _ in range(maxiter):
        Yi = lu_solve(Y, I, nb=nb, precision=_hi(precision))
        Zi = lu_solve(Z, I, nb=nb, precision=_hi(precision))
        Ynew = Y.with_local(0.5 * (Y.local + Zi.local))
        Z = Z.with_local(0.5 * (Z.local + Yi.local))
        delta = float(frobenius_norm(Y.with_local(Ynew.local - Y.local)))
        Y = Ynew
        if delta <= tol * max(float(frobenius_norm(Y)), 1e-30):
            break
    return Y


def hpd_square_root(A: DistMatrix, uplo: str = "L", nb: int | None = None,
                    precision=None) -> DistMatrix:
    """A^{1/2} of an HPD matrix via its eigendecomposition
    (``El::HPSDSquareRoot`` analog): Z diag(sqrt(w)) Z^H."""
    from ..blas.level1 import diagonal_scale
    from .spectral import herm_eig
    w, Z = herm_eig(A, uplo, vectors=True, nb=nb, precision=_hi(precision))
    sw = jnp.sqrt(jnp.clip(w, 0, None)).astype(A.dtype)
    d = DistMatrix(sw[:, None], (w.shape[0], 1), STAR, STAR, 0, 0, A.grid)
    Zs = diagonal_scale("R", d, Z)
    return gemm(Zs, Z, orient_b="C", nb=nb, precision=_hi(precision))


# ---------------------------------------------------------------------
# QDWH-eig: polar-based spectral divide and conquer
# ---------------------------------------------------------------------

def _replicated_eig(A: DistMatrix, vectors: bool):
    """Base case: gather the (small) block and solve redundantly."""
    n = A.gshape[0]
    Ag = redistribute(A, STAR, STAR).local
    w, Z = jnp.linalg.eigh(Ag)
    w = w.astype(_real_dtype(A.dtype))
    if not vectors:
        return w, None
    Zd = redistribute(
        DistMatrix(Z.astype(A.dtype), (n, n), STAR, STAR, 0, 0, A.grid),
        MC, MR)
    return w, Zd


def _dc_eig(A: DistMatrix, vectors: bool, nb, precision, base: int,
            seed: int, depth: int = 0):
    """Recursive QDWH-eig on a FULL (both triangles stored) Hermitian
    [MC,MR] matrix.  Returns (w ascending replicated, Z or None)."""
    n = A.gshape[0]
    g = A.grid
    if n <= max(base, 2) or depth > 60:
        return _replicated_eig(A, vectors)
    d = jnp.real(get_diagonal(A).local[:, 0])
    sigma = float(jnp.median(d))
    scale = max(float(frobenius_norm(A)), 1e-30)
    for attempt in range(3):
        As = shift_diagonal(A, -sigma)
        # U = sign(A - sigma I) via QDWH polar (Hermitian => polar == sign)
        U, _H = polar(As, nb=nb, precision=_hi(precision))
        # projector onto the eigenspace below sigma: P = (I - U)/2
        P = shift_diagonal(U.with_local(-0.5 * U.local), 0.5)
        k = int(round(float(jnp.real(dm_trace(P)))))
        if 0 < k < n:
            break
        # split failed: all eigenvalues on one side of sigma.  If the block
        # is (numerically) a multiple of the identity, deflate outright.
        rms = float(frobenius_norm(As)) / math.sqrt(n)
        if rms <= 10 * n * _eps_of(A.dtype) * scale:
            w = jnp.full((n,), sigma, _real_dtype(A.dtype))
            return (w, _identity_like(A, n) if vectors else None)
        sigma = sigma + rms if k == 0 else sigma - rms
    else:
        # could not find a splitting shift (pathological clustering):
        # correctness fallback
        return _replicated_eig(A, vectors)

    # orthonormal basis of range(P) via randomized range-finder + QR:
    # P is an exact projector up to rounding, so one multiply suffices and
    # the remaining Householder columns span the complement exactly.
    rng = np.random.default_rng(0xE1E0 + 31 * seed + depth)
    G = rng.normal(size=(n, k)).astype(np.float64)
    from ..core.distmatrix import from_global
    Gd = from_global(G.astype(np.dtype(_real_dtype(A.dtype))), MC, MR,
                     grid=g).astype(A.dtype)
    Y = gemm(P, Gd, nb=nb, precision=_hi(precision))
    Qp, tau = qr(Y, nb=nb, precision=_hi(precision))
    # C = Q^H A Q  (two packed-reflector applications + a transposition)
    T1 = apply_q(Qp, tau, A, orient="C", nb=nb, precision=_hi(precision))
    T2 = redistribute(transpose_dist(T1, conj=True), MC, MR)
    T3 = apply_q(Qp, tau, T2, orient="C", nb=nb, precision=_hi(precision))
    C = redistribute(transpose_dist(T3, conj=True), MC, MR)
    A1 = _hermitianize(interior_view(C, (0, k), (0, k)))
    A2 = _hermitianize(interior_view(C, (k, n), (k, n)))
    w1, Z1 = _dc_eig(A1, vectors, nb, precision, base, 2 * seed + 1, depth + 1)
    w2, Z2 = _dc_eig(A2, vectors, nb, precision, base, 2 * seed + 2, depth + 1)
    w = jnp.concatenate([w1, w2])
    if not vectors:
        return w, None
    BD = _blank(n, n, A)
    BD = interior_update(BD, Z1, (0, 0))
    BD = interior_update(BD, Z2, (k, k))
    Z = apply_q(Qp, tau, BD, orient="N", nb=nb, precision=_hi(precision))
    return w, Z


def _qdwh_eig(A: DistMatrix, uplo: str = "L", vectors: bool = True,
              subset=None, nb: int | None = None, precision=None,
              base: int | None = None):
    """Spectral divide-and-conquer eigensolver (QDWH-eig, the PMRRR
    replacement -- SURVEY.md §8.1 item 4).  No O(n^2)-replicated construct:
    splits ride :mod:`..redist.interior`, the base case gathers only
    ``base x base`` blocks."""
    from .spectral import _subset_slice
    _check_mcmr(A)
    n = A.gshape[0]
    if A.gshape != (n, n):
        raise ValueError(f"_qdwh_eig needs square, got {A.gshape}")
    full = make_symmetric(A, uplo, conj=True)
    base = base if base is not None else 128
    w, Z = _dc_eig(full, vectors, nb, precision, base, seed=1)
    # guard the seams: blocks are spectrum-ordered by construction, but
    # boundary rounding can micro-misorder; sort if needed.
    order = jnp.argsort(w)
    w = w[order]
    s, e = _subset_slice(w, subset)
    if not vectors:
        return w[s:e]
    from .lu import permute_cols
    Z = permute_cols(Z, order)
    if (s, e) != (0, n):
        Z = interior_view(Z, (0, n), (s, e))
    return w[s:e], Z
