"""LU with partial pivoting (HPL-style) + permutation utilities.

Reference: Elemental ``src/lapack_like/factor/LU.cpp`` +
``LU/{Panel,SolveAfter}.hpp`` and ``src/lapack_like/perm/`` (DistPermutation,
ApplyRowPivots) -- BASELINE.json's "LU with partial pivoting" config.

TPU-first redesign of the panel (SURVEY.md §4.4 / §8.3 item 2): the
reference's ``lu::Panel`` runs one MAXLOC AllReduce + one SendRecv PER
COLUMN -- a latency wall.  Here the whole current panel is gathered to
[STAR,STAR] (one collective) and factored REDUNDANTLY on every device with
a local ``lax.fori_loop``: identical deterministic results everywhere, so
pivot search costs zero communication.  The panel's composed row
permutation is applied to the trailing rows with one traced gather/scatter
on the storage array (the analog of HPL's row-broadcast swap).

Data-dependent pivots are traced values, so the whole factorization jits;
the packed L\\U layout and the permutation-vector convention follow LAPACK
getrf (perm[i] = original index of the row now at position i).
"""
from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax

from ..core.dist import MC, MR, STAR, VC, VR
from ..core.distmatrix import DistMatrix
from ..core.view import view, update_view
from ..redist.engine import redistribute
from ..blas.level3 import _blocksize, _check_mcmr, trsm

#: chunk-width ladder for the replicated panel factorization.  A/B-measured
#: on v5e at n=16384 nb=2048 (perf/ab_harness.py, same-process roofline
#: brackets): (512,64) 8.18/7.34 TFLOP/s across two runs vs (256,32) 6.53,
#: (256,64) 6.89, (1024,128) 6.92, (512,64,16) 4.89, (768,96) 7.46.
_INNERS = (512, 64)


def _hi(precision):
    """Precision policy of the lapack layer: with ``precision=None`` every
    matmul in a factorization/reduction driver runs at full f32
    accumulation (``Precision.HIGHEST``), matching the reference's f32
    BLAS semantics -- the default (bf16-input) matmul precision costs
    ~1e-2-level factor error on TPU, a silent accuracy downgrade.  An
    explicitly passed precision (including ``lax.Precision.DEFAULT`` for
    bf16-MXU throughput on the trailing updates) is honored unchanged."""
    return precision if precision is not None else lax.Precision.HIGHEST


# ---------------------------------------------------------------------
# permutation utilities (the DistPermutation analog)
# ---------------------------------------------------------------------

def permute_rows(B: DistMatrix, perm, inverse: bool = False) -> DistMatrix:
    """B[perm, :] as a DistMatrix (``DistPermutation::PermuteRows``).

    Rides [STAR,VR]: rows replicated there, so the traced-index gather is
    pure-local; two engine hops re-land [MC,MR]."""
    _check_mcmr(B)
    Bvr = redistribute(B, STAR, VR)
    p = jnp.argsort(perm) if inverse else perm
    out = Bvr.with_local(Bvr.local[p, :])
    return redistribute(out, MC, MR)


def permute_cols(B: DistMatrix, perm, inverse: bool = False) -> DistMatrix:
    """B[:, perm] as a DistMatrix (``DistPermutation::PermuteCols``).

    Rides [VC,STAR]: columns replicated there, so the traced-index gather is
    pure-local; two engine hops re-land [MC,MR]."""
    _check_mcmr(B)
    Bvc = redistribute(B, VC, STAR)
    p = jnp.argsort(perm) if inverse else perm
    out = Bvc.with_local(Bvc.local[:, p])
    return redistribute(out, MC, MR)


def _storage_row(i, r: int, lr: int):
    """Storage row of global row i for a stride-r zero-aligned dim."""
    if r == 1:
        return i
    return (i % r) * lr + i // r


def _apply_swaps_moved(A: DistMatrix, T, S, valid) -> DistMatrix:
    """Move global rows ``S`` to positions ``T`` on the storage array,
    dropping entries where ``valid`` is False (sentinel padding from
    :func:`_moved_rows`).  The storage row map is a bijection between
    slots and virtual indices, so invalid slots are forced out of range
    rather than trusting the sentinel's arithmetic image."""
    r, lr = A.col_stride, A.local_rows
    m = A.gshape[0]
    sidx = _storage_row(jnp.clip(T, 0, m - 1), r, lr)
    sidx = jnp.where(valid, sidx, r * lr)          # OOB => scatter drops
    gsrc = _storage_row(jnp.clip(S, 0, m - 1), r, lr)
    stor = A.local
    rows = jnp.take(stor, gsrc, axis=0)
    return A.with_local(stor.at[sidx].set(rows, mode="drop"))


# ---------------------------------------------------------------------
# replicated panel factorization
# ---------------------------------------------------------------------

def _panel_lu_unb(P, nbw: int):
    """Unblocked partial-pivot LU of a replicated (M, nbw) panel.

    Runs identically on every device (replicated input, deterministic) --
    the TPU answer to ``lu::Panel``'s per-column MAXLOC+SendRecv.
    Returns (packed L\\U panel, composed row permutation of the panel:
    output row i came from input row perm[i])."""
    M = P.shape[0]
    ridx = jnp.arange(M)
    cidx = jnp.arange(nbw)

    def body(j, state):
        P, perm = state
        col = P[:, j]
        cand = jnp.where(ridx >= j, jnp.abs(col), -jnp.inf)
        p = jnp.argmax(cand)
        rowj, rowp = P[j], P[p]
        P = P.at[j].set(rowp).at[p].set(rowj)
        pj, pp = perm[j], perm[p]
        perm = perm.at[j].set(pp).at[p].set(pj)
        pivval = P[j, j]
        l = jnp.where(ridx > j, P[:, j] / pivval, jnp.zeros_like(col))
        P = P.at[:, j].set(jnp.where(ridx > j, l, P[:, j]))
        urow = jnp.where(cidx > j, P[j], jnp.zeros_like(P[j]))
        P = P - jnp.outer(l, urow)
        return P, perm

    return lax.fori_loop(0, nbw, body, (P, jnp.arange(M)))


def _panel_lu(P, nbw: int, precision=None, inners=None):
    """Multi-level blocked panel: ``inners``-wide chunk recursion + matmul
    sub-updates.  The unblocked loop's per-column rank-1 update streams the
    whole chunk each iteration (bandwidth-bound at nbw sequential passes);
    narrowing the innermost chunk to 32 columns cuts that traffic ~nbw/32
    times while every chunk-to-chunk update is an MXU matmul.

    Returns (packed panel, composed row permutation of the panel)."""
    if inners is None:
        inners = _INNERS
    if not inners or nbw <= inners[-1]:
        return _panel_lu_unb(P, nbw)
    step, rest = inners[0], inners[1:]
    if nbw <= step:
        return _panel_lu(P, nbw, precision, rest)
    M = P.shape[0]
    perm = jnp.arange(M)
    for s in range(0, nbw, step):
        e = min(s + step, nbw)
        w = e - s
        sub, sperm = _panel_lu(P[s:, s:e], w, precision, rest)
        rows = jnp.take(P[s:], sperm, axis=0)          # apply swaps to block-row
        rows = rows.at[:, s:e].set(sub)
        if e < nbw:
            L11 = jnp.tril(sub[:w], -1) + jnp.eye(w, dtype=P.dtype)
            U12 = lax.linalg.triangular_solve(
                L11, rows[:w, e:], left_side=True, lower=True,
                unit_diagonal=True)
            rows = rows.at[:w, e:].set(U12)
            upd = jnp.matmul(sub[w:, :w], U12, precision=precision)
            rows = rows.at[w:, e:].set(rows[w:, e:] - upd.astype(P.dtype))
        P = P.at[s:].set(rows)
        perm = perm.at[s:].set(jnp.take(perm[s:], sperm, axis=0))
    return P, perm


def _unit_lower_inv(L11, nbw: int, precision=None, bs: int = 256):
    """Inverse of a unit-lower (nbw, nbw) panel block with matmul assembly
    (small triangular_solve only at ``bs`` diagonal blocks) -- turns the
    U12 := L11^{-1} A12 panel solve into one MXU matmul."""
    dt = L11.dtype
    if nbw <= bs:
        return lax.linalg.triangular_solve(
            L11, jnp.eye(nbw, dtype=dt), left_side=True, lower=True,
            unit_diagonal=True)
    Li = jnp.zeros((nbw, nbw), dt)
    for s in range(0, nbw, bs):
        e = min(s + bs, nbw)
        Likk = lax.linalg.triangular_solve(
            L11[s:e, s:e], jnp.eye(e - s, dtype=dt), left_side=True,
            lower=True, unit_diagonal=True)
        if s > 0:
            corr = jnp.matmul(
                Likk, jnp.matmul(L11[s:e, :s], Li[:s, :s],
                                 precision=_hi(precision)),
                precision=_hi(precision))
            Li = Li.at[s:e, :s].set(-corr.astype(dt))
        Li = Li.at[s:e, s:e].set(Likk)
    return Li


def _moved_rows(pperm, nbw: int):
    """Indices (into the trailing block) actually displaced by the composed
    panel permutation, padded to the static size 2*nbw with an out-of-range
    sentinel.  A composition of nbw swaps touches at most 2*nbw positions,
    so gather/scatter of just these rows replaces a full trailing-matrix
    row permutation (the dominant swap cost at large n)."""
    M = pperm.shape[0]
    k = min(2 * nbw, M)
    moved = pperm != jnp.arange(M)
    idx = jnp.nonzero(moved, size=k, fill_value=M)[0]
    src = pperm[jnp.clip(idx, 0, M - 1)]
    return idx, src


# ---------------------------------------------------------------------
# blocked right-looking LU
# ---------------------------------------------------------------------

def _local_lu(A: DistMatrix, nb: int | None, precision):
    """Sequential (p == 1) path: on a 1x1 grid the storage array IS the
    global matrix, so the blocked loop fuses into one XLA program with no
    redistribute sub-computation boundaries (the local ``Matrix<T>``
    dispatch of the reference)."""
    a = A.local
    m, n = A.gshape
    ib = max(nb or 1024, 1)
    kend = min(m, n)
    perm = jnp.arange(m)
    for s in range(0, kend, ib):
        e = min(s + ib, kend)
        nbw = e - s
        Pf, pperm = _panel_lu(a[s:, s:e], nbw, precision)
        perm = perm.at[s:].set(jnp.take(perm[s:], pperm, axis=0))
        # full trailing-block gather + contiguous writeback (TPU scatters
        # of dynamic row sets benchmark SLOWER than this full gather)
        a = a.at[s:].set(jnp.take(a[s:], pperm, axis=0))
        a = a.at[s:, s:e].set(Pf)
        if e < n:
            Li11 = _unit_lower_inv(jnp.tril(Pf[:nbw], -1)
                                   + jnp.eye(nbw, dtype=a.dtype),
                                   nbw, precision)
            U1n = jnp.matmul(Li11, a[s:e, e:], precision=_hi(precision)
                             ).astype(a.dtype)
            a = a.at[s:e, e:].set(U1n)
            if e < m:
                upd = jnp.matmul(Pf[nbw:], U1n, precision=precision)
                a = a.at[e:, e:].set(a[e:, e:] - upd.astype(a.dtype))
    return A.with_local(a), perm


def lu(A: DistMatrix, nb: int | None = None, precision=None):
    """Blocked right-looking LU with partial pivoting.

    Returns (LU, perm): LU holds unit-lower L below the diagonal and U on
    and above it (LAPACK getrf packing); perm is a traced length-m vector
    with perm[i] = original index of the row now at position i, so
    ``P A = L U`` with ``(P A)[i] = A[perm[i]]``."""
    _check_mcmr(A)
    m, n = A.gshape
    g = A.grid
    if g.size == 1:
        return _local_lu(A, nb, precision)
    r, c = g.height, g.width
    ib = _blocksize(nb, math.lcm(r, c), min(m, n))
    kend = min(m, n)
    perm = jnp.arange(m)
    for s in range(0, kend, ib):
        e = min(s + ib, kend)
        nbw = e - s
        # Views must start/end on stride boundaries; a ragged diagonal end
        # (wide matrices, e == m not stride-aligned) is handled by widening
        # every view to a legal boundary and column-masking the writebacks.
        e_up = min(-(-e // c) * c, n)
        panel = redistribute(view(A, rows=(s, m), cols=(s, e_up)), STAR, STAR)
        Pf, pperm = _panel_lu(panel.local[:, :nbw], nbw, precision)
        perm = perm.at[s:].set(jnp.take(perm[s:], pperm, axis=0))
        # move only the rows the panel permutation displaced (<= 2*nbw)
        # across ALL columns (the panel region is overwritten right after)
        idx, src = _moved_rows(pperm, nbw)
        valid = idx < (m - s)
        A = _apply_swaps_moved(A, idx + s, jnp.clip(src, 0, m - s - 1) + s,
                               valid)
        # write back the factored panel (rows s..m of cols s..e)
        if e_up > e:
            Pf_w = jnp.pad(Pf, ((0, 0), (0, e_up - e)))
        else:
            Pf_w = Pf
        Pf_ss = DistMatrix(Pf_w, (m - s, e_up - s), STAR, STAR, 0, 0, g)
        A = _update_cols_lt(A, redistribute(Pf_ss, MC, MR), (s, m), (s, e_up), e)
        # U12 := L11^{-1} A12 ; A22 -= L21 U12.  The solve runs over the full
        # legal column range (s, n) and the writeback keeps only cols >= e.
        if e < n:
            Li11 = _unit_lower_inv(jnp.tril(Pf[:nbw, :], -1)
                                   + jnp.eye(nbw, dtype=Pf.dtype),
                                   nbw, precision)
            A1n = redistribute(view(A, rows=(s, e), cols=(s, n)), STAR, VR)
            u1n = jnp.matmul(Li11, A1n.local, precision=_hi(precision)
                             ).astype(Pf.dtype)
            U1n = DistMatrix(u1n, (nbw, n - s), STAR, VR, 0, 0, g)
            U1n_mr = redistribute(U1n, STAR, MR)
            A = _update_cols_ge(A, redistribute(U1n_mr, MC, MR), (s, e), (s, n), e)
            if e < m:      # only non-final panels: e is stride-aligned here
                U12_mr = view(U1n_mr, cols=(e - s, n - s))
                L21_ss = DistMatrix(Pf[nbw:, :], (m - e, nbw), STAR, STAR, 0, 0, g)
                L21_mc = redistribute(L21_ss, MC, STAR)
                upd = jnp.matmul(L21_mc.local, U12_mr.local, precision=precision)
                A22 = view(A, rows=(e, m), cols=(e, n))
                A = update_view(A, A22.with_local(A22.local - upd.astype(A.dtype)),
                                rows=(e, m), cols=(e, n))
    return A, perm


def _blend_update(A: DistMatrix, block: DistMatrix, rows, cols, keep_new):
    from ..blas.level1 import _global_indices
    cur = view(A, rows=rows, cols=cols)
    I, J = _global_indices(cur)
    mask = keep_new(J)[None, :]
    return update_view(A, cur.with_local(jnp.where(mask, block.local, cur.local)),
                       rows=rows, cols=cols)


def _update_cols_lt(A, block, rows, cols, e):
    """Write ``block`` into the view, only at global columns < e."""
    if cols[1] == e:
        return update_view(A, block, rows=rows, cols=cols)
    return _blend_update(A, block, rows, cols, lambda J: J < e - cols[0])


def _update_cols_ge(A, block, rows, cols, e):
    """Write ``block`` into the view, only at global columns >= e."""
    return _blend_update(A, block, rows, cols, lambda J: J >= e - cols[0])


def lu_solve(A: DistMatrix, B: DistMatrix, nb: int | None = None,
             precision=None) -> DistMatrix:
    """Solve A X = B via LU with partial pivoting (``El::LinearSolve``,
    ``src/lapack_like/solve/LinearSolve.cpp``: LU + SolveAfter)."""
    LU_, perm = lu(A, nb=nb, precision=precision)
    return lu_solve_after(LU_, perm, B, nb=nb, precision=precision)


def lu_solve_after(LU_: DistMatrix, perm, B: DistMatrix, nb: int | None = None,
                   precision=None) -> DistMatrix:
    """X = U^{-1} L^{-1} P B (``lu::SolveAfter``)."""
    Bp = permute_rows(B, perm)
    Y = trsm("L", "L", "N", LU_, Bp, unit=True, nb=nb, precision=precision)
    return trsm("L", "U", "N", LU_, Y, nb=nb, precision=precision)


def lu_full_pivot(A: DistMatrix, precision=None):
    """LU with COMPLETE pivoting: ``P A Q = L U`` with the pivot the
    largest remaining |entry| each step (``lu::Full``, Elemental
    ``src/lapack_like/factor/LU/Full.hpp``).

    Returns ``(LU, rperm, cperm)`` with the getrf-style packed factor and
    row/column permutations: ``(P A Q)[i, j] = A[rperm[i], cperm[j]]``.

    Runs REPLICATED on the gathered matrix (one jitted fori_loop: the
    per-step global argmax serializes everything -- the reference's
    complete-pivot variant is likewise its slow, maximum-stability path;
    use :func:`lu` (partial pivoting) for speed)."""
    _check_mcmr(A)
    m, n = A.gshape
    kend = min(m, n)
    g = A.grid
    a = redistribute(A, STAR, STAR).local
    ridx = jnp.arange(m)
    cidx = jnp.arange(n)

    def body(j, state):
        a, rp, cp = state
        absa = jnp.abs(a)
        mask = (ridx[:, None] >= j) & (cidx[None, :] >= j)
        cand = jnp.where(mask, absa, -jnp.inf)
        flat = jnp.argmax(cand)
        pi, pj = flat // n, flat % n
        # row swap j <-> pi
        rj, rpv = a[j], a[pi]
        a = a.at[j].set(rpv).at[pi].set(rj)
        rp = rp.at[j].set(rp[pi]).at[pi].set(rp[j])
        # col swap j <-> pj
        cj, cpv = a[:, j], a[:, pj]
        a = a.at[:, j].set(cpv).at[:, pj].set(cj)
        cp = cp.at[j].set(cp[pj]).at[pj].set(cp[j])
        piv = a[j, j]
        safe = jnp.where(piv == 0, 1, piv)
        l = jnp.where(ridx > j, a[:, j] / safe, jnp.zeros_like(a[:, j]))
        a = a.at[:, j].set(jnp.where(ridx > j, l, a[:, j]))
        urow = jnp.where(cidx > j, a[j], jnp.zeros_like(a[j]))
        a = a - jnp.outer(l, urow)
        return a, rp, cp

    a, rp, cp = lax.fori_loop(0, kend, body,
                              (a, jnp.arange(m), jnp.arange(n)))
    LU_ = redistribute(DistMatrix(a, (m, n), STAR, STAR, 0, 0, g), MC, MR)
    return LU_, rp, cp
