"""LU with partial pivoting (HPL-style, look-ahead pipelined) + permutation
utilities.

Reference: Elemental ``src/lapack_like/factor/LU.cpp`` +
``LU/{Panel,SolveAfter}.hpp`` and ``src/lapack_like/perm/`` (DistPermutation,
ApplyRowPivots) -- BASELINE.json's "LU with partial pivoting" config.

TPU-first redesign of the panel (SURVEY.md §4.4 / §8.3 item 2): the
reference's ``lu::Panel`` runs one MAXLOC AllReduce + one SendRecv PER
COLUMN -- a latency wall.  Here the whole current panel is gathered to
[STAR,STAR] (one collective) and factored REDUNDANTLY on every device with
a local ``lax.fori_loop``: identical deterministic results everywhere, so
pivot search costs zero communication.  The panel's composed row
permutation is applied to the trailing rows with one traced gather/scatter
on the storage array (the analog of HPL's row-broadcast swap).

Communication-avoiding panel (``panel='calu'``, ISSUE 6): tournament
pivoting replaces even the replicated per-column pivot chain -- per-grid-
row slab LUs, a log-depth playoff of candidate pivot blocks, ONE batched
storage-level row permutation per panel, an unpivoted MXU-friendly
refactorization, and a one-psum row-block solve.  See :func:`lu` and the
README's "Communication-avoiding LU" section; ``panel='classic'``
(default) is byte-for-byte the schedule described above.

Look-ahead schedule (the HPL pipeline; default on)
--------------------------------------------------
The classic right-looking driver serializes panel -> swap -> solve ->
update every step, so the latency-bound replicated panel factorization
sits on the critical path ``n/nb`` times.  The pipelined driver instead
splits step k's trailing update by columns into (a) the NEXT panel's
strip and (b) the wide remainder:

    swap + write back panel k                    (from the carried factor)
    U_k  := L11^{-1} A(k, k+1:)                  (one row-block solve)
    strip := A22[:, :nb] - L21 U_k[:, :nb]       (a: narrow update)
    factor panel k+1 from ``strip``              (off the critical path)
    rest := A22[:, nb:] - L21 U_k[:, nb:]        (b: wide MXU update)

The strip/rest operands are captured BEFORE any writeback, so the panel
k+1 factorization and the wide remainder matmul share no data dependence
and XLA is free to overlap them (async collectives on a grid, scheduler
freedom on one chip).  Everything stays one traced program per
(shape, grid): no host sync between phases.

Precision split (``update_precision``)
--------------------------------------
``precision`` governs the panel factorization and the triangular/row-block
solves (default f32 accumulation via :func:`_hi`).  ``update_precision``,
when given, applies ONLY to the trailing ``L21 @ U12`` updates -- passing
``lax.Precision.DEFAULT`` runs them on the bf16 MXU path (~6x the f32-class
matmul rate on TPU).  This is opt-in: bf16 trailing updates raise the
``||P A - L U|| / ||A||`` residual from ~1e-6 to the ~1e-3 level at
n=16384 (each entry of the Schur complement accumulates bf16 rounding
``n/nb`` times), which is still small relative to partial pivoting's
growth bound but well above the f32 default.  Leave it ``None`` for
bit-equivalent-to-classic factors.

Phase timing (``timer``)
------------------------
Pass a ``perf.phase_timer.PhaseTimer``-shaped object (``start()`` +
``tick(phase, step, *arrays)``) and call ``lu`` EAGERLY (outside jit): the
driver synchronizes at every panel / swap / solve / update boundary and the
timer attributes per-step wall-clock.  ``python perf/ab_harness.py phases``
emits the resulting JSON.  With ``timer=None`` (default) the hooks are
dead code and the driver jits as one fused program.

Data-dependent pivots are traced values, so the whole factorization jits;
the packed L\\U layout and the permutation-vector convention follow LAPACK
getrf (perm[i] = original index of the row now at position i).
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..core.compat import shard_map
from ..core.dist import MC, MR, STAR, VC, VR
from ..core.distmatrix import DistMatrix
from ..core.view import view, update_view
from ..redist.engine import (apply_fault, move_rows, permute_rows_storage,
                             redistribute)
from ..redist.quantize import check_comm_precision, quantizable
from ..blas.level3 import _blocksize, _check_mcmr, local_rank_update, trsm

#: chunk-width ladder for the replicated panel factorization.  A/B-measured
#: on v5e at n=16384 nb=2048 (perf/ab_harness.py, same-process roofline
#: brackets): (512,64) 8.18/7.34 TFLOP/s across two runs vs (256,32) 6.53,
#: (256,64) 6.89, (1024,128) 6.92, (512,64,16) 4.89, (768,96) 7.46.
#: The pinned tuple now lives in ``kernels.DEFAULT_INNERS`` (single
#: source shared with the ``panel_impl`` dispatch and bench provenance
#: -- ISSUE 17); sweep with ``perf/ab_harness.py lu`` (which passes
#: ``inners=`` explicitly, no module monkeypatching) and re-pin THERE.
#: This module-level alias survives for historical importers only.
from ..kernels import default_inners as _default_inners
from ..kernels import resolve_panel as _resolve_panel

_INNERS = _default_inners()


def _hi(precision):
    """Precision policy of the lapack layer: with ``precision=None`` every
    matmul in a factorization/reduction driver runs at full f32
    accumulation (``Precision.HIGHEST``), matching the reference's f32
    BLAS semantics -- the default (bf16-input) matmul precision costs
    ~1e-2-level factor error on TPU, a silent accuracy downgrade.  An
    explicitly passed precision (including ``lax.Precision.DEFAULT`` for
    bf16-MXU throughput on the trailing updates) is honored unchanged."""
    return precision if precision is not None else lax.Precision.HIGHEST


# The zero-overhead null tick hook and the driver-entry hook resolver now
# live in the observability subsystem (ISSUE 5); the historical name is
# kept for this module's importers (cholesky, tests).
from ..obs.tracer import NULL_HOOK as _NULL_TIMER, phase_hook as _phase_hook


# ---------------------------------------------------------------------
# permutation utilities (the DistPermutation analog)
# ---------------------------------------------------------------------

def permute_rows(B: DistMatrix, perm, inverse: bool = False) -> DistMatrix:
    """B[perm, :] as a DistMatrix (``DistPermutation::PermuteRows``).

    Zero-aligned [MC,MR] rides the engine's one-shot storage gather
    (``permute_rows_storage``, the batched-permutation fast path -- no
    explicit collective rounds); misaligned inputs keep the historical
    [STAR,VR] route: rows replicated there, so the traced-index gather is
    pure-local, and two engine hops re-land [MC,MR]."""
    _check_mcmr(B)
    if (B.calign, B.ralign) == (0, 0):
        return permute_rows_storage(B, perm, inverse=inverse)
    Bvr = redistribute(B, STAR, VR)
    p = jnp.argsort(perm) if inverse else perm
    out = Bvr.with_local(Bvr.local[p, :])
    return redistribute(out, MC, MR)


def permute_cols(B: DistMatrix, perm, inverse: bool = False) -> DistMatrix:
    """B[:, perm] as a DistMatrix (``DistPermutation::PermuteCols``).

    Rides [VC,STAR]: columns replicated there, so the traced-index gather is
    pure-local; two engine hops re-land [MC,MR]."""
    _check_mcmr(B)
    Bvc = redistribute(B, VC, STAR)
    p = jnp.argsort(perm) if inverse else perm
    out = Bvc.with_local(Bvc.local[:, p])
    return redistribute(out, MC, MR)


def _apply_swaps_moved(A: DistMatrix, T, S, valid) -> DistMatrix:
    """Move global rows ``S`` to positions ``T`` in one batched pass,
    dropping entries where ``valid`` is False (sentinel padding from
    :func:`_moved_rows`).  Thin wrapper over the engine's storage-level
    batched-permutation fast path (``redist.engine.move_rows``), kept
    under its historical name for this module's importers."""
    return move_rows(A, T, S, valid)


# ---------------------------------------------------------------------
# replicated panel factorization
# ---------------------------------------------------------------------

def _panel_lu_unb(P, nbw: int):
    """Unblocked partial-pivot LU of a replicated (M, nbw) panel.

    Runs identically on every device (replicated input, deterministic) --
    the TPU answer to ``lu::Panel``'s per-column MAXLOC+SendRecv.
    Returns (packed L\\U panel, composed row permutation of the panel:
    output row i came from input row perm[i])."""
    M = P.shape[0]
    ridx = jnp.arange(M)
    cidx = jnp.arange(nbw)

    def body(j, state):
        P, perm = state
        col = P[:, j]
        cand = jnp.where(ridx >= j, jnp.abs(col), -jnp.inf)
        p = jnp.argmax(cand)
        rowj, rowp = P[j], P[p]
        P = P.at[j].set(rowp).at[p].set(rowj)
        pj, pp = perm[j], perm[p]
        perm = perm.at[j].set(pp).at[p].set(pj)
        pivval = P[j, j]
        l = jnp.where(ridx > j, P[:, j] / pivval, jnp.zeros_like(col))
        P = P.at[:, j].set(jnp.where(ridx > j, l, P[:, j]))
        urow = jnp.where(cidx > j, P[j], jnp.zeros_like(P[j]))
        P = P - jnp.outer(l, urow)
        return P, perm

    return lax.fori_loop(0, nbw, body, (P, jnp.arange(M)))


def _panel_lu(P, nbw: int, precision=None, inners=None):
    """Multi-level blocked panel: ``inners``-wide chunk recursion + matmul
    sub-updates.  The unblocked loop's per-column rank-1 update streams the
    whole chunk each iteration (bandwidth-bound at nbw sequential passes);
    narrowing the innermost chunk to 32 columns cuts that traffic ~nbw/32
    times while every chunk-to-chunk update is an MXU matmul.

    Returns (packed panel, composed row permutation of the panel)."""
    if inners is None:
        inners = _INNERS
    if not inners or nbw <= inners[-1]:
        return _panel_lu_unb(P, nbw)
    step, rest = inners[0], inners[1:]
    if nbw <= step:
        return _panel_lu(P, nbw, precision, rest)
    M = P.shape[0]
    perm = jnp.arange(M)
    for s in range(0, nbw, step):
        e = min(s + step, nbw)
        w = e - s
        sub, sperm = _panel_lu(P[s:, s:e], w, precision, rest)
        rows = jnp.take(P[s:], sperm, axis=0)          # apply swaps to block-row
        rows = rows.at[:, s:e].set(sub)
        if e < nbw:
            L11 = jnp.tril(sub[:w], -1) + jnp.eye(w, dtype=P.dtype)
            U12 = lax.linalg.triangular_solve(
                L11, rows[:w, e:], left_side=True, lower=True,
                unit_diagonal=True)
            rows = rows.at[:w, e:].set(U12)
            upd = jnp.matmul(sub[w:, :w], U12, precision=precision)
            rows = rows.at[w:, e:].set(rows[w:, e:] - upd.astype(P.dtype))
        P = P.at[s:].set(rows)
        perm = perm.at[s:].set(jnp.take(perm[s:], sperm, axis=0))
    return P, perm


def _panel_dispatch(P, nbw: int, precision=None, plan=None):
    """Route one replicated panel through the resolved ``panel_impl``
    plan (``kernels.PanelPlan``): the fused Pallas kernel when the plan
    says so AND the panel passes the static VMEM/dtype gate, else the
    XLA chunk ladder with the plan's ``inners``.  ``plan=None`` is the
    status-quo ladder -- every historical caller is unchanged."""
    if plan is not None and plan.use_pallas(P.shape, P.dtype):
        from ..kernels import lu_panel
        return lu_panel(P, nbw, precision, inner=plan.pallas_inner)
    inners = plan.inners if plan is not None else None
    return _panel_lu(P, nbw, precision, inners)


# ---------------------------------------------------------------------
# CALU tournament-pivoted panel (communication-avoiding LU, cf.
# Grigori/Demmel/Xiang and the TPU distributed-linear-algebra paper
# arXiv 2112.09017): each grid row factors its cyclic slab of the panel
# with ordinary partial pivoting, the per-slab candidate pivot blocks
# reduce in a log-depth pairwise-LU playoff tree, and the winning rows
# are applied as ONE composed row permutation per panel.  The permuted
# panel then factors WITHOUT pivoting: an nb x nb unpivoted diagonal
# factorization plus a single MXU matmul for the whole L21 block --
# no per-column argmax or data-dependent row swap over the panel height,
# which is exactly the latency wall of the classic panel.
# ---------------------------------------------------------------------

def _playoff_perm(V, ncol: int):
    """Pivot ORDER of a masked partial-pivot LU sweep over a (possibly
    zero-padded) block: returns the composed permutation only (the factor
    values are discarded -- playoffs select rows, the real factorization
    happens once on the winners).  Divisions are guarded so all-zero
    padding rows flow through as zeros instead of NaNs."""
    Mp, w = V.shape
    ridx = jnp.arange(Mp)
    cidx = jnp.arange(w)

    def body(j, state):
        V, perm = state
        cand = jnp.where(ridx >= j, jnp.abs(V[:, j]), -jnp.inf)
        p = jnp.argmax(cand)
        rowj, rowp = V[j], V[p]
        V = V.at[j].set(rowp).at[p].set(rowj)
        pj, pp = perm[j], perm[p]
        perm = perm.at[j].set(pp).at[p].set(pj)
        piv = V[j, j]
        safe = jnp.where(piv == 0, jnp.ones_like(piv), piv)
        l = jnp.where(ridx > j, V[:, j] / safe, jnp.zeros_like(V[:, j]))
        V = V.at[:, j].set(jnp.where(ridx > j, l, V[:, j]))
        urow = jnp.where(cidx > j, V[j], jnp.zeros_like(V[j]))
        return V - jnp.outer(l, urow), perm

    _, perm = lax.fori_loop(0, min(ncol, Mp), body, (V, jnp.arange(Mp)))
    return perm


def _tournament_pivots(P, nbw: int, r: int):
    """The CALU tournament: composed panel permutation (perm[i] = original
    row now at position i) whose first ``nbw`` entries are the playoff
    winners.  Runs replicated and deterministic on every device (same
    zero-communication pattern as the classic replicated panel): slab
    membership mirrors the [MC,*] ownership map (global row i lives in
    grid row i % r), so the simulated tournament selects exactly the
    pivots a message-passing CALU over the grid rows would."""
    M = P.shape[0]
    lslab = max(-(-M // r), nbw)
    sidx = jnp.arange(lslab)[None, :] * r + jnp.arange(r)[:, None]
    ok = sidx < M                                       # (r, lslab)
    vals = jnp.where(ok[:, :, None], P[jnp.clip(sidx, 0, M - 1)], 0)
    gidx = jnp.where(ok, sidx, M)                       # sentinel M = padding
    # round 0: every slab's local partial-pivot sweep (vmapped -- the
    # replicated image of r independent, communication-free local LUs)
    sperm = jax.vmap(lambda v: _playoff_perm(v, nbw))(vals)
    top = sperm[:, :nbw]
    cvals = jnp.take_along_axis(vals, top[:, :, None], axis=1)
    cidx = jnp.take_along_axis(gidx, top, axis=1)       # (r, nbw)
    # log-depth pairwise playoffs (odd participant gets a bye)
    nblk = r
    while nblk > 1:
        half, odd = nblk // 2, nblk % 2
        lo_v, hi_v = cvals[:half], cvals[half:2 * half]
        lo_i, hi_i = cidx[:half], cidx[half:2 * half]
        st_v = jnp.concatenate([lo_v, hi_v], axis=1)    # (half, 2*nbw, nbw)
        st_i = jnp.concatenate([lo_i, hi_i], axis=1)
        pperm = jax.vmap(lambda v: _playoff_perm(v, nbw))(st_v)
        wtop = pperm[:, :nbw]
        wv = jnp.take_along_axis(st_v, wtop[:, :, None], axis=1)
        wi = jnp.take_along_axis(st_i, wtop, axis=1)
        if odd:
            wv = jnp.concatenate([wv, cvals[2 * half:]], axis=0)
            wi = jnp.concatenate([wi, cidx[2 * half:]], axis=0)
        cvals, cidx = wv, wi
        nblk = half + odd
    win = cidx[0]                                       # (nbw,) global rows
    # compose the one-shot permutation: winner j swaps into position j
    # (a padding sentinel degenerates to a no-op swap; only reachable on
    # exactly-singular panels, where classic pivoting is arbitrary too)
    def body(j, state):
        perm, invp = state
        w = jnp.where(win[j] < M, win[j], perm[j])
        tp = invp[w]
        pj = perm[j]
        perm = perm.at[j].set(w).at[tp].set(pj)
        invp = invp.at[w].set(j).at[pj].set(tp)
        return perm, invp

    perm, _ = lax.fori_loop(0, nbw, body, (jnp.arange(M), jnp.arange(M)))
    return perm


def _lu_nopiv(W, precision=None, bs: int = 256):
    """Unpivoted blocked LU of a square block (packed L\\U, unit-lower L).
    The CALU diagonal factorization: the tournament already fixed the
    pivot order, so no argmax / row motion remains -- diagonal blocks run
    the plain recurrence, off-diagonal blocks are triangular solves and
    one MXU matmul per step."""
    b = W.shape[0]

    def unb(B):
        n = B.shape[0]
        idx = jnp.arange(n)

        def body(j, B):
            l = jnp.where(idx > j, B[:, j] / B[j, j], jnp.zeros_like(B[:, j]))
            B = B.at[:, j].set(jnp.where(idx > j, l, B[:, j]))
            urow = jnp.where(idx > j, B[j], jnp.zeros_like(B[j]))
            return B - jnp.outer(l, urow)

        return lax.fori_loop(0, n, body, B)

    if b <= bs:
        return unb(W)
    for s in range(0, b, bs):
        e = min(s + bs, b)
        blk = unb(W[s:e, s:e])
        W = W.at[s:e, s:e].set(blk)
        if e < b:
            L11 = jnp.tril(blk, -1) + jnp.eye(e - s, dtype=W.dtype)
            U12 = lax.linalg.triangular_solve(
                L11, W[s:e, e:], left_side=True, lower=True,
                unit_diagonal=True)
            L21 = lax.linalg.triangular_solve(
                jnp.triu(blk), W[e:, s:e], left_side=False, lower=False)
            W = W.at[s:e, e:].set(U12).at[e:, s:e].set(L21)
            upd = jnp.matmul(L21, U12, precision=_hi(precision))
            W = W.at[e:, e:].set(W[e:, e:] - upd.astype(W.dtype))
    return W


def _upper_inv(U, nbw: int, precision=None, bs: int = 256):
    """Inverse of a non-unit upper-triangular block with matmul assembly
    (the upper sibling of :func:`_unit_lower_inv`) -- turns the CALU
    ``L21 := A21 U11^{-1}`` panel solve into one MXU matmul."""
    dt = U.dtype
    if nbw <= bs:
        return lax.linalg.triangular_solve(
            U, jnp.eye(nbw, dtype=dt), left_side=True, lower=False)
    Ui = jnp.zeros((nbw, nbw), dt)
    for s in range(0, nbw, bs):
        e = min(s + bs, nbw)
        Uikk = lax.linalg.triangular_solve(
            U[s:e, s:e], jnp.eye(e - s, dtype=dt), left_side=True,
            lower=False)
        if s > 0:
            corr = jnp.matmul(
                jnp.matmul(Ui[:s, :s], U[:s, s:e], precision=_hi(precision)),
                Uikk, precision=_hi(precision))
            Ui = Ui.at[:s, s:e].set(-corr.astype(dt))
        Ui = Ui.at[s:e, s:e].set(Uikk)
    return Ui


def _nopiv_panel(Pp, nbw: int, precision=None):
    """Unpivoted factorization of an already-permuted (M, nbw) panel:
    packed ``[L11\\U11; L21]`` with ``L21 = A21 U11^{-1}`` as one matmul.
    Shared by the CALU panel (winners on top) and the TSQR Householder
    reconstruction in ``qr.py`` (LU of ``Q1 - S``)."""
    Wf = _lu_nopiv(Pp[:nbw], precision)
    Ui = _upper_inv(jnp.triu(Wf), nbw, precision)
    L21 = jnp.matmul(Pp[nbw:], Ui, precision=_hi(precision)).astype(Pp.dtype)
    return jnp.concatenate([Wf, L21], axis=0)


def _calu_panel(P, nbw: int, r: int, precision=None):
    """CALU panel factorization of a replicated (M, nbw) panel: tournament
    pivot selection over ``r`` grid-row slabs + unpivoted refactorization
    of the permuted panel.  Same ``(packed, perm)`` contract as
    :func:`_panel_lu`, so the look-ahead / crossover machinery consumes it
    unchanged.  With ``r == 1`` the tournament IS partial pivoting (one
    slab, winners = the PP pivots), so the classic panel is called
    directly -- bit-identical pivots on single-row grids."""
    M = P.shape[0]
    if r <= 1 or M <= nbw:
        return _panel_lu(P, nbw, precision)
    perm = _tournament_pivots(P, nbw, r)
    Pp = jnp.take(P, perm, axis=0)
    return _nopiv_panel(Pp, nbw, precision), perm


def _unit_lower_inv(L11, nbw: int, precision=None, bs: int = 256):
    """Inverse of a unit-lower (nbw, nbw) panel block with matmul assembly
    (small triangular_solve only at ``bs`` diagonal blocks) -- turns the
    U12 := L11^{-1} A12 panel solve into one MXU matmul."""
    dt = L11.dtype
    if nbw <= bs:
        return lax.linalg.triangular_solve(
            L11, jnp.eye(nbw, dtype=dt), left_side=True, lower=True,
            unit_diagonal=True)
    Li = jnp.zeros((nbw, nbw), dt)
    for s in range(0, nbw, bs):
        e = min(s + bs, nbw)
        Likk = lax.linalg.triangular_solve(
            L11[s:e, s:e], jnp.eye(e - s, dtype=dt), left_side=True,
            lower=True, unit_diagonal=True)
        if s > 0:
            corr = jnp.matmul(
                Likk, jnp.matmul(L11[s:e, :s], Li[:s, :s],
                                 precision=_hi(precision)),
                precision=_hi(precision))
            Li = Li.at[s:e, :s].set(-corr.astype(dt))
        Li = Li.at[s:e, s:e].set(Likk)
    return Li


def _moved_rows(pperm, nbw: int):
    """Indices (into the trailing block) actually displaced by the composed
    panel permutation, padded to the static size 2*nbw with an out-of-range
    sentinel.  A composition of nbw swaps touches at most 2*nbw positions,
    so gather/scatter of just these rows replaces a full trailing-matrix
    row permutation (the dominant swap cost at large n)."""
    M = pperm.shape[0]
    k = min(2 * nbw, M)
    moved = pperm != jnp.arange(M)
    idx = jnp.nonzero(moved, size=k, fill_value=M)[0]
    src = pperm[jnp.clip(idx, 0, M - 1)]
    return idx, src


# ---------------------------------------------------------------------
# one-collective row-block solve (the CALU schedule's U12 path)
# ---------------------------------------------------------------------

@partial(jax.jit, static_argnums=(2, 3))
def _rowblock_solve_jit(Ablk: DistMatrix, Li11, precision, wire=None):
    """``U = Li11 @ Ablk`` for an (nbw, w) [MC,MR] row block, landing
    [STAR,MR] in ONE psum round.

    The classic schedule moves the row block to [STAR,VR] (an all_to_all),
    multiplies locally, and promotes VR -> MR (an all_gather): two
    collective rounds per panel.  Here each device contracts the
    replicated ``Li11`` against only the block rows it already stores
    (columns ``mc + r*iLoc`` of ``Li11``) and one ``psum`` over the grid
    column completes the product -- the contraction is genuinely
    distributed over grid rows, r-fold less panel-solve compute per
    device AND one round instead of two.

    ``wire='bf16'`` runs the psum on a bfloat16 payload (the
    ``comm_precision`` path: reductions never ride int8 -- integer
    accumulation would overflow the block scale -- so both quantized
    modes reduce at bf16; local math stays at ``precision``)."""
    g = Ablk.grid
    r = g.height
    nbw = Ablk.gshape[0]
    out_meta = DistMatrix(None, Ablk.gshape, STAR, MR, 0, 0, g)

    def f(ab, L):
        mc = lax.axis_index("mc")
        lr = ab.local.shape[0]
        cols = mc + r * jnp.arange(lr)
        okc = cols < nbw
        Lsub = jnp.take(L, jnp.clip(cols, 0, nbw - 1), axis=1)
        Lsub = jnp.where(okc[None, :], Lsub, 0)
        part = jnp.matmul(Lsub, ab.local, precision=precision)
        if wire == "bf16":
            out = lax.psum(part.astype(jnp.bfloat16), "mc").astype(part.dtype)
        else:
            out = lax.psum(part, "mc")
        return DistMatrix(out, ab.gshape, STAR, MR, 0, ab.ralign, g)

    from jax.sharding import PartitionSpec as P
    return shard_map(
        f, mesh=g.mesh, in_specs=(Ablk.spec, P(None, None)),
        out_specs=out_meta.spec, check_vma=False,
    )(Ablk, Li11)


# ---------------------------------------------------------------------
# blocked right-looking LU with look-ahead
# ---------------------------------------------------------------------

def _local_lu(A: DistMatrix, nb: int | None, precision,
              update_precision=None, lookahead: bool = True, timer=None,
              plan=None):
    """Sequential (p == 1) path: on a 1x1 grid the storage array IS the
    global matrix, so the blocked loop fuses into one XLA program with no
    redistribute sub-computation boundaries (the local ``Matrix<T>``
    dispatch of the reference).  ``lookahead=True`` runs the pipelined
    schedule from the module docstring; ``False`` keeps the classic
    right-looking order (the A/B baseline)."""
    a, perm = _local_lu_array(A.local, A.gshape[0], A.gshape[1],
                              max(nb or 1024, 1), precision,
                              update_precision, lookahead, timer, plan)
    return A.with_local(a), perm


def _local_lu_array(a, m: int, n: int, ib: int, precision,
                    update_precision=None, lookahead: bool = True,
                    timer=None, plan=None):
    """Blocked LU of a plain (replicated) array: the sequential engine
    behind both the 1x1-grid path and the distributed loop's
    crossover-to-local tail.  Returns ``(packed LU array, perm)``."""
    kend = min(m, n)
    perm = jnp.arange(m)
    upd = precision if update_precision is None else update_precision
    tm = timer if timer is not None else _NULL_TIMER
    tm.start()
    if lookahead:
        w0 = min(ib, kend)
        nxt = _panel_dispatch(a[:, :w0], w0, precision, plan)
        tm.tick("panel", 0, nxt)
    for k, s in enumerate(range(0, kend, ib)):
        e = min(s + ib, kend)
        nbw = e - s
        if lookahead:
            Pf, pperm = nxt
        else:
            Pf, pperm = _panel_dispatch(a[s:, s:e], nbw, precision, plan)
            tm.tick("panel", k, Pf, pperm)
        perm = perm.at[s:].set(jnp.take(perm[s:], pperm, axis=0))
        # full trailing-block gather + contiguous writeback (TPU scatters
        # of dynamic row sets benchmark SLOWER than this full gather)
        a = a.at[s:].set(jnp.take(a[s:], pperm, axis=0))
        tm.tick("swap", k, a)
        a = a.at[s:, s:e].set(Pf)
        if e >= n:
            continue
        Li11 = _unit_lower_inv(jnp.tril(Pf[:nbw], -1)
                               + jnp.eye(nbw, dtype=a.dtype),
                               nbw, precision)
        U1n = jnp.matmul(Li11, a[s:e, e:], precision=_hi(precision)
                         ).astype(a.dtype)
        tm.tick("solve", k, U1n)
        if not lookahead or e >= kend:
            a = a.at[s:e, e:].set(U1n)
            if e < m:
                u = jnp.matmul(Pf[nbw:], U1n, precision=upd)
                a = a.at[e:, e:].set(a[e:, e:] - u.astype(a.dtype))
                tm.tick("update", k, a)
            continue
        # look-ahead: (a) narrow strip update -> factor panel k+1 off the
        # critical path -> (b) wide remainder update.  Both updates read
        # the pre-writeback ``a``, so XLA sees them as independent.
        e2 = min(e + ib, kend)
        w = e2 - e
        L21 = Pf[nbw:]
        strip = a[e:, e:e2] - jnp.matmul(L21, U1n[:, :w],
                                         precision=upd).astype(a.dtype)
        nxt = _panel_dispatch(strip, w, precision, plan)
        tm.tick("panel", k + 1, nxt)
        a = a.at[s:e, e:].set(U1n)
        if e2 < n:
            rest = a[e:, e2:] - jnp.matmul(L21, U1n[:, w:],
                                           precision=upd).astype(a.dtype)
            a = a.at[e:, e2:].set(rest)
        # the strip region a[e:, e:e2] is dead from here on: step k+1's
        # swap + panel writeback fully overwrite it, so skipping its
        # writeback saves one (m-e) x nb store per step
        tm.tick("update", k, a)
    return a, perm


#: default crossover-to-local threshold for the look-ahead schedule (the
#: Cholesky PR-2 trade, same default): once the trailing block is at most
#: this size, ONE [STAR,STAR] gather + a replicated local finish replaces
#: the remaining per-step collective latency.  A trailing t x t block
#: costs ~t/nb more panel gathers + solve rounds distributed, vs one
#: gather of t^2 words here -- latency-bound for small t on real meshes.
_CROSSOVER = 4096


def lu(A: DistMatrix, nb: int | str | None = None, precision=None,
       update_precision=None, lookahead: bool | str = True,
       crossover: int | str | None = None, panel: str = "classic",
       panel_impl: str | None = None, inners=None,
       comm_precision: str | None = None, redist_path: str | None = None,
       timer=None, health=None, abft=None):
    """Blocked right-looking LU with partial pivoting and look-ahead.

    Returns (LU, perm): LU holds unit-lower L below the diagonal and U on
    and above it (LAPACK getrf packing); perm is a traced length-m vector
    with perm[i] = original index of the row now at position i, so
    ``P A = L U`` with ``(P A)[i] = A[perm[i]]``.

    ``crossover`` is the trailing-block size at which the distributed loop
    gathers the remaining (rows x cols <= crossover^2) block once,
    finishes it with the replicated sequential kernel, and applies the
    tail's row permutation in one storage-level pass (``None`` =
    :data:`_CROSSOVER` with look-ahead, disabled classic; 0 never crosses
    over).  ``lookahead`` selects the pipelined schedule (module
    docstring); ``update_precision`` optionally lowers ONLY the trailing
    ``L21 @ U12``
    updates (e.g. ``lax.Precision.DEFAULT`` for bf16-MXU throughput at a
    documented ~1e-3 residual cost); ``timer`` enables eager per-phase
    wall-clock attribution (see ``perf/phase_timer.py``).

    ``panel`` selects the panel strategy:

      * ``'classic'`` (default) -- replicated partial-pivot panel, the
        bit-exactness A/B + stability baseline.
      * ``'calu'`` -- communication-avoiding tournament pivoting
        (:func:`_calu_panel`): per-grid-row slab LUs, a log-depth playoff
        of candidate pivot blocks, one batched row permutation per panel,
        an unpivoted MXU-friendly panel refactorization, and a
        one-``psum`` row-block solve (:func:`_rowblock_solve_jit`) in
        place of the classic two-round [STAR,VR] dance.  Pivots differ
        from partial pivoting (growth factor bounded by the tournament,
        not by 2^k -- see README "Communication-avoiding LU"); on
        single-row grids (r == 1, incl. 1x1) calu degenerates to classic
        exactly.  The crossover tail finishes with the local classic
        kernel under either strategy.

    ``panel_impl`` (``None`` | ``'xla'`` | ``'pallas'`` | ``'auto'``)
    selects the panel IMPLEMENTATION, orthogonal to the ``panel``
    strategy above: ``'pallas'`` runs the classic replicated panel as
    ONE fused VMEM-resident kernel (``kernels.lu_panel``: pivot search,
    row swaps, column scales, and chunk-blocked trailing updates in a
    single launch; off-TPU it executes under ``interpret=True``), while
    ``None``/``'xla'`` keep the status-quo chunk ladder.  The fused
    kernel's pivot sequence is bit-identical to the ladder's unblocked
    base case (same first-max argmax tie-break, pinned by
    ``tests/kernels``); complex dtypes and panels whose working set
    exceeds the VMEM budget fall back to the XLA twin silently (the
    knob is a performance hint, never a semantics change).  Tree panels
    (``panel='calu'`` tournaments) keep their XLA slab kernels -- the
    knob covers the classic primitives, including the sequential tail.
    ``inners`` optionally overrides the chunk-width ladder
    (``kernels.DEFAULT_INNERS``) for BOTH implementations; the A/B
    harness sweeps it through this argument.

    ``comm_precision`` (``None`` | ``'bf16'`` | ``'int8'``) selects the
    WIRE precision of the schedule's bulk redistributions (panel gathers,
    the U12 row-block transport, the crossover tail gather; the CALU
    row-block psum reduces at bf16 under either mode): payloads are
    block-scale encoded before each collective and decoded after, so
    gathers move 2-4x fewer bytes at identical round counts while all
    local math keeps ``precision``.  Opt-in: ``None`` (default) is
    bit-identical to the unquantized schedule (pinned by tests).  bf16
    wire raises the factor residual to the ~1e-2..1e-3 relative level
    (int8 similar; see README "Quantized collectives") -- pair with
    ``resilience.certified_solve`` for certified answers.

    ``redist_path`` (``None`` | ``'chain'`` | ``'direct'`` | ``'auto'``)
    selects the redistribution ROUTE of the same bulk moves: ``'direct'``
    compiles each dist change into a one-shot collective plan
    (``redist.plan``), ``'auto'`` arbitrates per move via the engine's
    chain-vs-plan cost mirror, ``None``/``'chain'`` keep the factored
    multi-hop chain (bit-identical baseline, pinned by the comm-plan
    goldens).

    ``nb`` / ``lookahead`` / ``crossover`` / ``panel`` /
    ``comm_precision`` / ``redist_path`` accept ``'auto'``: the tuning
    subsystem (``elemental_tpu/tune``) resolves them per (shape, dtype,
    grid, backend) -- measured-cache winner first, analytic cost model
    cold; explicit values always win.  ``panel='auto'`` picks calu on
    multi-row grids and classic on single-row ones (the pivot latency
    term of the cost model).

    ``health`` opts into the resilience subsystem's numerical-health
    guards (``elemental_tpu/resilience``): pass a ``HealthMonitor`` (read
    ``monitor.report()`` afterwards) or ``True`` (report retrievable via
    ``resilience.last_health_report('lu')``).  The monitor rides the same
    tick hook as ``timer`` -- NaN/Inf scans, a growth-factor estimate,
    and near-zero pivot detection at every phase boundary, engine-free.
    ``health=None`` (default) attaches nothing: the zero-overhead
    NULL_HOOK path, pinned by the redist-count goldens.

    ``abft`` opts into checksum-guarded execution with panel-granular
    recovery (``elemental_tpu/resilience/abft.py``, ISSUE 11): pass
    ``True`` (report via ``resilience.last_abft_report('lu')``) or a
    caller-owned ``AbftGuard``.  The guarded path verifies
    Huang-Abraham column-sum invariants after every transport / panel
    factor / trailing update and, on violation, rolls back and
    re-executes ONLY the corrupted panel step (bounded retries, then
    surfaces through ``health_report/v1``).  It forces the CLASSIC
    right-looking schedule on every grid (``lookahead`` / ``crossover``
    / ``panel='calu'`` are ignored: pipelining and tournament pivoting
    do not compose with per-panel transactions).  ``abft=None``
    (default) is the unguarded zero-overhead path, bit-identical to
    before -- pinned by the comm-plan goldens."""
    _check_mcmr(A)
    if any(isinstance(v, str) for v in (nb, lookahead, crossover)) \
            or panel == "auto" or comm_precision == "auto" \
            or redist_path == "auto" or panel_impl == "auto":
        from ..tune.policy import resolve_knobs
        kn = resolve_knobs("lu", gshape=A.gshape, dtype=A.dtype, grid=A.grid,
                           knobs={"nb": nb, "lookahead": lookahead,
                                  "crossover": crossover, "panel": panel,
                                  "panel_impl": panel_impl,
                                  "comm_precision": comm_precision,
                                  "redist_path": redist_path})
        nb, lookahead, crossover = kn["nb"], kn["lookahead"], kn["crossover"]
        panel, comm_precision = kn["panel"], kn["comm_precision"]
        redist_path = kn["redist_path"]
        panel_impl = kn["panel_impl"]
    check_comm_precision(comm_precision)
    rp = redist_path
    plan = _resolve_panel(panel_impl, dtype=A.dtype, inners=inners)
    if abft:
        from ..resilience.abft import abft_lu
        return abft_lu(A, nb=nb, precision=precision,
                       update_precision=update_precision,
                       comm_precision=comm_precision, timer=timer,
                       health=health, abft=abft, plan=plan)
    if panel is None:
        panel = "classic"
    if panel not in ("classic", "calu"):
        raise ValueError(f"lu: unknown panel strategy {panel!r}; "
                         "expected 'classic', 'calu', or 'auto'")
    m, n = A.gshape
    g = A.grid
    tm = _phase_hook("lu", timer)
    hm = None
    if health:
        from ..resilience.health import attach_health
        tm, hm = attach_health("lu", health, tm, scale_from=A)
    if g.size == 1:
        out = _local_lu(A, nb, precision, update_precision, lookahead, tm,
                        plan)
        if hm is not None:
            hm.report()
        return out
    r, c = g.height, g.width
    calu = panel == "calu" and r > 1

    def factor_panel(Ploc, w: int, step: int):
        """One panel under the selected strategy; ticks the tournament
        phase (obs) between pivot selection and the unpivoted refactor.
        The packed result routes through the engine's 'compute' fault
        seam (identity unless a FaultPlan is installed -- ISSUE 9)."""
        if not calu or Ploc.shape[0] <= w:
            Pf, pperm = _panel_dispatch(Ploc, w, precision, plan)
        else:
            pperm = _tournament_pivots(Ploc, w, r)
            tm.tick("tournament", step, pperm)
            Pp = jnp.take(Ploc, pperm, axis=0)
            Pf = _nopiv_panel(Pp, w, precision)
        Pf, = apply_fault("compute", (Pf,))
        return Pf, pperm

    ib = _blocksize(nb, math.lcm(r, c), min(m, n))
    kend = min(m, n)
    perm = jnp.arange(m)
    upd = precision if update_precision is None else update_precision
    xover = (_CROSSOVER if lookahead else 0) if crossover is None \
        else max(int(crossover), 0)
    tm.start()

    def col_up(e):
        # Views must start/end on stride boundaries; a ragged diagonal end
        # (wide matrices, e == m not stride-aligned) is handled by widening
        # every view to a legal boundary and column-masking the writebacks.
        return min(-(-e // c) * c, n)

    if lookahead:
        e0_up = col_up(min(ib, kend))
        panel0 = redistribute(view(A, rows=(0, m), cols=(0, e0_up)),
                              STAR, STAR, comm_precision=comm_precision,
                              path=rp)
        nxt = factor_panel(panel0.local[:, :min(ib, kend)], min(ib, kend), 0)
        tm.tick("panel", 0, nxt)
    for k, s in enumerate(range(0, kend, ib)):
        e = min(s + ib, kend)
        nbw = e - s
        e_up = col_up(e)
        # crossover-to-local: after this step's update the remaining
        # (m-e) x (n-e) trailing block is small enough that ONE gather +
        # a replicated sequential finish beats the per-step collective
        # latency of the remaining steps (e is stride-aligned: e < kend)
        tail = bool(xover) and e < kend and m - e <= xover and n - e <= xover
        if lookahead:
            Pf, pperm = nxt
        else:
            panel = redistribute(view(A, rows=(s, m), cols=(s, e_up)),
                                 STAR, STAR,
                                 comm_precision=comm_precision, path=rp)
            Pf, pperm = factor_panel(panel.local[:, :nbw], nbw, k)
            tm.tick("panel", k, Pf, pperm)
        perm = perm.at[s:].set(jnp.take(perm[s:], pperm, axis=0))
        # move only the rows the panel permutation displaced (<= 2*nbw)
        # across ALL columns (the panel region is overwritten right after)
        idx, src = _moved_rows(pperm, nbw)
        valid = idx < (m - s)
        A = _apply_swaps_moved(A, idx + s, jnp.clip(src, 0, m - s - 1) + s,
                               valid)
        tm.tick("swap", k, A)
        # write back the factored panel (rows s..m of cols s..e)
        if e_up > e:
            Pf_w = jnp.pad(Pf, ((0, 0), (0, e_up - e)))
        else:
            Pf_w = Pf
        Pf_ss = DistMatrix(Pf_w, (m - s, e_up - s), STAR, STAR, 0, 0, g)
        A = _update_cols_lt(A, redistribute(Pf_ss, MC, MR), (s, m), (s, e_up), e)
        # U12 := L11^{-1} A12 ; A22 -= L21 U12.  The solve runs over the full
        # legal column range (s, n) and the writeback keeps only cols >= e.
        if e >= n:
            continue
        Li11 = _unit_lower_inv(jnp.tril(Pf[:nbw, :], -1)
                               + jnp.eye(nbw, dtype=Pf.dtype),
                               nbw, precision)
        if calu:
            # one-psum row-block solve: the contraction over the block's
            # rows distributes across grid rows and a single psum lands
            # [STAR,MR] -- one round instead of the classic all_to_all +
            # all_gather pair below
            U1n_mr = _rowblock_solve_jit(
                view(A, rows=(s, e), cols=(s, n)), Li11, _hi(precision),
                "bf16" if comm_precision and quantizable(A.dtype) else None)
        else:
            A1n = redistribute(view(A, rows=(s, e), cols=(s, n)),
                               STAR, VR, comm_precision=comm_precision,
                               path=rp)
            u1n = jnp.matmul(Li11, A1n.local, precision=_hi(precision)
                             ).astype(Pf.dtype)
            U1n = DistMatrix(u1n, (nbw, n - s), STAR, VR, 0, 0, g)
            U1n_mr = redistribute(U1n, STAR, MR,
                                  comm_precision=comm_precision, path=rp)
        tm.tick("solve", k, U1n_mr)
        if not lookahead or e >= kend:
            A = _update_cols_ge(A, redistribute(U1n_mr, MC, MR), (s, e),
                                (s, n), e)
            if e < m:      # only non-final panels: e is stride-aligned here
                U12_mr = view(U1n_mr, cols=(e - s, n - s))
                L21_ss = DistMatrix(Pf[nbw:, :], (m - e, nbw), STAR, STAR,
                                    0, 0, g)
                L21_mc = redistribute(L21_ss, MC, STAR)
                A = local_rank_update(A, L21_mc.local, U12_mr.local,
                                      rows=(e, m), cols=(e, n),
                                      precision=upd)
                tm.tick("update", k, A)
            if tail:
                A, perm = _lu_tail(A, perm, e, ib, precision, upd,
                                   lookahead, tm, k, comm_precision, rp,
                                   plan)
                break
            continue
        # look-ahead: split the trailing update at the next panel boundary.
        # All operands are captured from the PRE-writeback A, so the panel
        # k+1 factorization and the wide remainder matmul are data-
        # independent and free to overlap.
        e2 = min(e + ib, kend)
        e2_up = col_up(e2)
        L21_ss = DistMatrix(Pf[nbw:, :], (m - e, nbw), STAR, STAR, 0, 0, g)
        L21_mc = redistribute(L21_ss, MC, STAR)
        U12a = view(U1n_mr, cols=(e - s, e2_up - s))
        A22a = view(A, rows=(e, m), cols=(e, e2_up))
        stripD = A22a.with_local(
            A22a.local - jnp.matmul(L21_mc.local, U12a.local,
                                    precision=upd).astype(A.dtype))
        if not tail:
            # factor panel k+1 from the freshly updated strip (gshape
            # already (m-e, e2_up-e) from the view metadata); skipped when
            # the tail finish below refactors the whole trailing block
            strip_ss = redistribute(stripD, STAR, STAR,
                                    comm_precision=comm_precision, path=rp)
            nxt = factor_panel(strip_ss.local[:, :e2 - e], e2 - e, k + 1)
            tm.tick("panel", k + 1, nxt)
        # (b) wide remainder update, cols >= e2_up
        if e2_up < n:
            U12b = view(U1n_mr, cols=(e2_up - s, n - s))
            A22b = view(A, rows=(e, m), cols=(e2_up, n))
            restD = A22b.with_local(
                A22b.local - jnp.matmul(L21_mc.local, U12b.local,
                                        precision=upd).astype(A.dtype))
        else:
            restD = None
        # writebacks (U row block, strip, remainder)
        A = _update_cols_ge(A, redistribute(U1n_mr, MC, MR), (s, e),
                            (s, n), e)
        A = update_view(A, stripD, rows=(e, m), cols=(e, e2_up))
        if restD is not None:
            A = update_view(A, restD, rows=(e, m), cols=(e2_up, n))
        tm.tick("update", k, A)
        if tail:
            A, perm = _lu_tail(A, perm, e, ib, precision, upd, lookahead,
                               tm, k, comm_precision, rp, plan)
            break
    if hm is not None:
        hm.report()
    return A, perm


def _lu_tail(A: DistMatrix, perm, e: int, ib: int, precision, upd,
             lookahead: bool, tm, k: int, comm_precision=None,
             redist_path=None, plan=None):
    """Crossover-to-local finish of the (fully updated) trailing block.

    One [STAR,STAR] gather of rows/cols >= e, a replicated run of the
    sequential blocked kernel (identical deterministic results on every
    device, like the panel factorization), one storage-level row
    permutation of the already-factored left columns, and a pure-local
    scatter of the factored tail -- the remaining t/nb steps of per-step
    collective latency collapse into a single round trip."""
    m, n = A.gshape
    g = A.grid
    Atail = redistribute(view(A, rows=(e, m), cols=(e, n)), STAR, STAR,
                         comm_precision=comm_precision, path=redist_path)
    at, pt = _local_lu_array(Atail.local, m - e, n - e, ib, precision,
                             upd, lookahead, plan=plan)
    # the tail's composed row permutation applies to the WHOLE row range
    # (the left factored columns must see the same swaps); cols >= e are
    # overwritten by the factored-tail writeback right after
    A = _apply_swaps_moved(A, jnp.arange(m - e) + e, pt + e,
                           jnp.ones(m - e, dtype=bool))
    At_ss = DistMatrix(at, (m - e, n - e), STAR, STAR, 0, 0, g)
    A = update_view(A, redistribute(At_ss, MC, MR), rows=(e, m), cols=(e, n))
    perm = perm.at[e:].set(jnp.take(perm[e:], pt, axis=0))
    tm.tick("tail", k, A)
    return A, perm


def _blend_update(A: DistMatrix, block: DistMatrix, rows, cols, keep_new):
    from ..blas.level1 import _global_indices
    cur = view(A, rows=rows, cols=cols)
    I, J = _global_indices(cur)
    mask = keep_new(J)[None, :]
    return update_view(A, cur.with_local(jnp.where(mask, block.local, cur.local)),
                       rows=rows, cols=cols)


def _update_cols_lt(A, block, rows, cols, e):
    """Write ``block`` into the view, only at global columns < e."""
    if cols[1] == e:
        return update_view(A, block, rows=rows, cols=cols)
    return _blend_update(A, block, rows, cols, lambda J: J < e - cols[0])


def _update_cols_ge(A, block, rows, cols, e):
    """Write ``block`` into the view, only at global columns >= e."""
    return _blend_update(A, block, rows, cols, lambda J: J >= e - cols[0])


def lu_solve(A: DistMatrix, B: DistMatrix, nb: int | None = None,
             precision=None, panel: str = "classic", info: bool = False,
             health=None):
    """Solve A X = B via LU with partial pivoting (``El::LinearSolve``,
    ``src/lapack_like/solve/LinearSolve.cpp``: LU + SolveAfter).
    ``panel`` selects the factorization's panel strategy (see :func:`lu`);
    the solve-after path is strategy-agnostic -- it only consumes the
    packed factor and the composed permutation.

    ``info=True`` returns ``(X, info)`` where ``info`` is the structured
    singularity signal ``{"singular", "diag_index", "finite"}`` from the
    factor's diagonal (an exactly-singular A surfaces as a zero pivot
    instead of a silently NaN/Inf X -- eager-mode only, like ``timer``);
    ``health`` forwards to :func:`lu` (the resilience guards).  For the
    full residual-certified path use
    ``elemental_tpu.resilience.certified_solve('lu', A, B)``."""
    LU_, perm = lu(A, nb=nb, precision=precision, panel=panel,
                   health=health)
    X = lu_solve_after(LU_, perm, B, nb=nb, precision=precision)
    if not info:
        return X
    from ..resilience.health import factor_diag_info
    return X, factor_diag_info("lu", LU_)


def lu_solve_after(LU_: DistMatrix, perm, B: DistMatrix, nb: int | None = None,
                   precision=None) -> DistMatrix:
    """X = U^{-1} L^{-1} P B (``lu::SolveAfter``)."""
    Bp = permute_rows(B, perm)
    Y = trsm("L", "L", "N", LU_, Bp, unit=True, nb=nb, precision=precision)
    return trsm("L", "U", "N", LU_, Y, nb=nb, precision=precision)


def lu_full_pivot(A: DistMatrix, precision=None):
    """LU with COMPLETE pivoting: ``P A Q = L U`` with the pivot the
    largest remaining |entry| each step (``lu::Full``, Elemental
    ``src/lapack_like/factor/LU/Full.hpp``).

    Returns ``(LU, rperm, cperm)`` with the getrf-style packed factor and
    row/column permutations: ``(P A Q)[i, j] = A[rperm[i], cperm[j]]``.

    Runs REPLICATED on the gathered matrix (one jitted fori_loop: the
    per-step global argmax serializes everything -- the reference's
    complete-pivot variant is likewise its slow, maximum-stability path;
    use :func:`lu` (partial pivoting) for speed)."""
    _check_mcmr(A)
    m, n = A.gshape
    kend = min(m, n)
    g = A.grid
    a = redistribute(A, STAR, STAR).local
    ridx = jnp.arange(m)
    cidx = jnp.arange(n)

    def body(j, state):
        a, rp, cp = state
        absa = jnp.abs(a)
        mask = (ridx[:, None] >= j) & (cidx[None, :] >= j)
        cand = jnp.where(mask, absa, -jnp.inf)
        flat = jnp.argmax(cand)
        pi, pj = flat // n, flat % n
        # row swap j <-> pi
        rj, rpv = a[j], a[pi]
        a = a.at[j].set(rpv).at[pi].set(rj)
        rp = rp.at[j].set(rp[pi]).at[pi].set(rp[j])
        # col swap j <-> pj
        cj, cpv = a[:, j], a[:, pj]
        a = a.at[:, j].set(cpv).at[:, pj].set(cj)
        cp = cp.at[j].set(cp[pj]).at[pj].set(cp[j])
        piv = a[j, j]
        safe = jnp.where(piv == 0, 1, piv)
        l = jnp.where(ridx > j, a[:, j] / safe, jnp.zeros_like(a[:, j]))
        a = a.at[:, j].set(jnp.where(ridx > j, l, a[:, j]))
        urow = jnp.where(cidx > j, a[j], jnp.zeros_like(a[j]))
        a = a - jnp.outer(l, urow)
        return a, rp, cp

    a, rp, cp = lax.fori_loop(0, kend, body,
                              (a, jnp.arange(m), jnp.arange(n)))
    LU_ = redistribute(DistMatrix(a, (m, n), STAR, STAR, 0, 0, g), MC, MR)
    return LU_, rp, cp
