"""Dense LDL^T / LDL^H with Bunch-Kaufman pivoting + symmetric solves.

Reference: Elemental ``src/lapack_like/factor/LDL.cpp`` +
``LDL/dense/{Var3,Pivoted}.hpp`` (``El::LDL``, ``LDLPivotType`` --
BUNCH_KAUFMAN_A is the default partial-pivoting strategy) and
``src/lapack_like/solve/`` (``El::SymmetricSolve``/``HermitianSolve``).

TPU-first design (the lu.py pattern, LAPACK ``lasyf``-style left-looking
panel): one jitted ``lax.fori_loop`` per panel factors columns [s, e) of
the symmetric matrix.  Every column the loop touches -- the pivot column
AND a Bunch-Kaufman 2x2 candidate's partner column (which may live OUTSIDE
the panel) -- is read uniformly as ``snapshot column - L W^H correction``,
where the snapshot is the full symmetric storage array at panel start and
the traced-index column gather is resolved by GSPMD (zero communication on
one device; a cheap dynamic-slice collective otherwise).  The trailing
update is one masked [MC,STAR] x [STAR,MR] storage matmul per panel (the
MXU rank-nb form of the reference's ``Trrk``-based update).

Documented deviation from LAPACK sytrf: a 2x2 pivot never CROSSES a panel
boundary -- on the last panel column the better of the two 1x1 choices
(|a_kk| vs the partner's |a_rr|) is taken instead.  Growth stays bounded in
practice (oracle-tested incl. pivot-stress cases); pass ``nb >= n`` for
LAPACK-faithful pivot sequences on moderate sizes.

Packing: ``ldl`` returns ``(Lp, d, e, perm)``: unit-lower L in Lp's
strictly-lower triangle (D's diagonal on Lp's diagonal for display), D's
diagonal in ``d`` and subdiagonal in ``e`` (``e[j] != 0`` marks a 2x2 block
at (j, j+1)), and the row permutation ``perm``: ``(P A P^T) = L D L^H``
with ``(P A P^T)[i, j] = A[perm[i], perm[j]]``.
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dist import MC, MR, STAR, VR
from ..core.distmatrix import DistMatrix
from ..core.view import view, update_view
from ..redist.engine import redistribute
from ..blas.level1 import make_symmetric
from ..blas.level3 import _blocksize, _check_mcmr, trsm
from .lu import permute_rows, _update_cols_lt, _hi

_ALPHA = (1.0 + math.sqrt(17.0)) / 8.0


def _real_dtype(dtype):
    return jnp.zeros((), dtype).real.dtype


@partial(jax.jit, static_argnums=(1, 2, 3, 4, 5, 6))
def _panel_ldl(stor, s: int, m: int, nbw: int, conjugate: bool,
               Sc: int, Sr: int):
    """Bunch-Kaufman panel over global rows/cols [s, m) x [s, s+nbw).

    ``stor`` is the full SYMMETRIC stacked-storage array (panel-start
    snapshot).  Returns (L, W, d, e, perm): L unit-lower (mt, nbw) panel and
    W = L D, both with rows in the PERMUTED order; perm maps output panel
    row i -> input panel row perm[i]."""
    mt = m - s
    dtype = stor.dtype
    rdtype = _real_dtype(dtype) if conjugate else dtype
    ridx = jnp.arange(mt)
    _conj = jnp.conj if conjugate else (lambda x: x)
    lr = -(-m // Sc)                   # storage rows per residue class

    def snap_col(gcol, perm):
        """Snapshot column ``gcol`` (traced global index), panel rows in
        permuted order."""
        scol = ((gcol % Sr) * (-(-m // Sr)) + gcol // Sr)
        colf = lax.dynamic_index_in_dim(stor, scol, axis=1, keepdims=False)
        grow = s + perm                                   # (mt,) traced
        srow = (grow % Sc) * lr + grow // Sc
        return jnp.take(colf, srow, axis=0)

    def col(cprime, L, W, k, perm):
        """Corrected column cprime of the permuted trailing matrix."""
        base = snap_col(s + perm[cprime], perm)
        wrow = jnp.where(jnp.arange(nbw) < k, _conj(W[cprime, :]), 0)
        return base - L @ wrow

    def swap_rows(x, i, j):
        xi, xj = x[i], x[j]
        return x.at[i].set(xj).at[j].set(xi)

    def body(k, carry):
        L, W, d, e, perm, skip = carry

        def do_col(args):
            L, W, d, e, perm = args
            wk = col(k, L, W, k, perm)
            absakk = jnp.abs(wk[k])
            tail = jnp.where(ridx > k, jnp.abs(wk), -1.0)
            imax = jnp.argmax(tail)
            colmax = jnp.maximum(tail[imax], 0.0)

            wr = col(imax, L, W, k, perm)
            rowtail = jnp.where((ridx >= k) & (ridx != imax),
                                jnp.abs(wr), -1.0)
            rowmax = jnp.maximum(jnp.max(rowtail), jnp.finfo(rdtype).tiny)
            absarr = jnp.abs(wr[imax])

            no_col = colmax <= 0
            t11 = no_col | (absakk >= _ALPHA * colmax * (colmax / rowmax))
            t11s = (~t11) & (absarr >= _ALPHA * rowmax)
            last = k == nbw - 1
            t22 = (~t11) & (~t11s) & (~last)
            # boundary fallback: the better 1x1 (swap iff partner is larger)
            t11s = t11s | ((~t11) & last & (absarr > absakk))
            t11 = ~(t11s | t22)

            def branch_11(_):
                # pivot row: k (plain) or imax (swapped)
                src = jnp.where(t11s, imax, k)
                permn = swap_rows(perm, k, src)
                Ln = jnp.take(L, swap_rows(ridx, k, src), axis=0)
                Wn = jnp.take(W, swap_rows(ridx, k, src), axis=0)
                w = jnp.where(t11s, swap_rows(wr, k, src),
                              swap_rows(wk, k, src))
                dk = w[k]
                dk_safe = jnp.where(dk == 0, 1, dk)
                lcol = jnp.where(ridx > k, w / dk_safe, 0).at[k].set(1)
                wcol = jnp.where(ridx >= k, w, 0)
                Ln = Ln.at[:, k].set(lcol.astype(dtype))
                Wn = Wn.at[:, k].set(wcol.astype(dtype))
                dreal = jnp.real(dk) if conjugate else dk
                dn = d.at[k].set(jnp.asarray(dreal, d.dtype))
                return Ln, Wn, dn, e, permn, jnp.asarray(False)

            def branch_22(_):
                k1 = jnp.minimum(k + 1, mt - 1)
                permn = swap_rows(perm, k1, imax)
                Ln = jnp.take(L, swap_rows(ridx, k1, imax), axis=0)
                Wn = jnp.take(W, swap_rows(ridx, k1, imax), axis=0)
                w1 = swap_rows(wk, k1, imax)
                w2 = swap_rows(wr, k1, imax)
                d11, d21 = w1[k], w1[k1]
                d22 = w2[k1]
                off = _conj(d21)
                det = d11 * d22 - d21 * off
                det = jnp.where(det == 0, 1, det)
                i11, i12 = d22 / det, -off / det
                i21, i22 = -d21 / det, d11 / det
                mrows = ridx > k1
                l1 = jnp.where(mrows, w1 * i11 + w2 * i21, 0).at[k].set(1)
                l2 = jnp.where(mrows, w1 * i12 + w2 * i22, 0).at[k1].set(1)
                kc = jnp.minimum(k + 1, nbw - 1)
                Ln = Ln.at[:, k].set(l1.astype(dtype))
                Ln = Ln.at[:, kc].set(l2.astype(dtype))
                Wn = Wn.at[:, k].set(jnp.where(ridx >= k, w1, 0).astype(dtype))
                Wn = Wn.at[:, kc].set(jnp.where(ridx >= k, w2, 0).astype(dtype))
                dr1 = jnp.real(d11) if conjugate else d11
                dr2 = jnp.real(d22) if conjugate else d22
                dn = d.at[k].set(jnp.asarray(dr1, d.dtype))
                dn = dn.at[kc].set(jnp.asarray(dr2, d.dtype))
                en = e.at[k].set(jnp.asarray(d21, e.dtype))
                return Ln, Wn, dn, en, permn, jnp.asarray(True)

            return lax.cond(t22, branch_22, branch_11, None)

        def skip_col(args):
            L, W, d, e, perm = args
            return L, W, d, e, perm, jnp.asarray(False)

        L, W, d, e, perm, was22 = lax.cond(
            skip, skip_col, do_col, (L, W, d, e, perm))
        return L, W, d, e, perm, was22

    init = (jnp.zeros((mt, nbw), dtype), jnp.zeros((mt, nbw), dtype),
            jnp.zeros((nbw,), rdtype), jnp.zeros((nbw,), dtype),
            jnp.arange(mt), jnp.asarray(False))
    L, W, d, e, perm, _ = lax.fori_loop(0, nbw, body, init)
    return L, W, d, e, perm


def _apply_sym_perm(A: DistMatrix, s: int, pperm) -> DistMatrix:
    """Symmetrically permute global rows AND cols [s, m) by ``pperm`` on the
    stacked storage (two traced gathers)."""
    m, n = A.gshape
    Sc, Sr = A.col_stride, A.row_stride
    lr, lc = A.local_rows, A.local_cols
    stor = A.local
    grow = s + pperm
    srow_dst = (jnp.arange(s, m) % Sc) * lr + jnp.arange(s, m) // Sc
    srow_src = (grow % Sc) * lr + grow // Sc
    stor = stor.at[srow_dst].set(jnp.take(stor, srow_src, axis=0))
    scol_dst = (jnp.arange(s, m) % Sr) * lc + jnp.arange(s, m) // Sr
    scol_src = (grow % Sr) * lc + grow // Sr
    stor = stor.at[:, scol_dst].set(jnp.take(stor, scol_src, axis=1))
    return A.with_local(stor)


def ldl(A: DistMatrix, uplo: str = "L", conjugate: bool | None = None,
        nb: int | None = None, precision=None):
    """Pivoted LDL factorization of a symmetric/Hermitian [MC,MR] matrix
    (``El::LDL`` with Bunch-Kaufman-A pivoting).  Reads the ``uplo``
    triangle; ``conjugate`` selects LDL^H (default for complex input) vs
    LDL^T.  Returns ``(Lp, d, e, perm)`` (see module docstring)."""
    _check_mcmr(A)
    m = A.gshape[0]
    if A.gshape != (m, m):
        raise ValueError(f"ldl needs square, got {A.gshape}")
    if conjugate is None:
        conjugate = jnp.issubdtype(A.dtype, jnp.complexfloating)
    g = A.grid
    r, c = g.height, g.width
    full = make_symmetric(A, uplo, conj=conjugate)
    ib = _blocksize(nb, math.lcm(r, c), m)
    Sc, Sr = full.col_stride, full.row_stride
    d_parts, e_parts = [], []
    gperm = jnp.arange(m)
    for s in range(0, m, ib):
        e_col = min(s + ib, m)
        nbw = e_col - s
        L, W, dpan, epan, pperm = _panel_ldl(full.local, s, m, nbw,
                                             conjugate, Sc, Sr)
        d_parts.append(dpan)
        e_parts.append(epan)
        gperm = gperm.at[s:].set(jnp.take(gperm[s:], pperm, axis=0))
        full = _apply_sym_perm(full, s, pperm)
        # write the packed panel: L below the diagonal, D's diagonal on it
        packed = jnp.tril(L, -1)
        didx = jnp.arange(nbw)
        packed = packed.at[didx, didx].set(dpan.astype(L.dtype))
        blk = DistMatrix(packed, (m - s, nbw), STAR, STAR, 0, 0, g)
        e_up = min(-(-e_col // c) * c, m)
        if e_up > e_col:
            wpad = jnp.pad(packed, ((0, 0), (0, e_up - e_col)))
            blk = DistMatrix(wpad, (m - s, e_up - s), STAR, STAR, 0, 0, g)
        full = _update_cols_lt(full, redistribute(blk, MC, MR),
                               (s, m), (s, e_up), e_col)
        if e_col == m:
            break
        # trailing update: A22 -= L2 W2^H (full storage kept symmetric, so
        # update BOTH triangles -- two matmul-free halves would need the
        # mask anyway; one full product keeps later panels' snapshot valid)
        nt = m - e_col
        L2 = L[nbw:, :]
        W2 = W[nbw:, :]
        _c = jnp.conj if conjugate else (lambda x: x)
        L2_mc = redistribute(DistMatrix(L2, (nt, nbw), STAR, STAR, 0, 0, g),
                             MC, STAR)
        W2H_mr = redistribute(DistMatrix(_c(W2).T, (nbw, nt), STAR, STAR,
                                         0, 0, g), STAR, MR)
        A22 = view(full, rows=(e_col, m), cols=(e_col, m))
        upd = jnp.matmul(L2_mc.local, W2H_mr.local, precision=_hi(precision))
        full = update_view(full, A22.with_local(A22.local - upd.astype(A.dtype)),
                           rows=(e_col, m), cols=(e_col, m))
    d = jnp.concatenate(d_parts)
    # subdiagonal has length m-1 (a panel boundary never hosts a 2x2)
    e_ = jnp.concatenate(e_parts)[:max(m - 1, 0)]
    return full, d, e_, gperm


def _block_diag_solve(d, e, Y: DistMatrix, conjugate: bool) -> DistMatrix:
    """X = D^{-1} Y for the Bunch-Kaufman block-diagonal D (replicated d/e;
    rows paired on [STAR,VR] where they are local)."""
    m = Y.gshape[0]
    Yvr = redistribute(Y, STAR, VR)
    y = Yvr.local
    dtype = y.dtype
    dd = d.astype(dtype)
    ee = jnp.concatenate([e.astype(dtype), jnp.zeros((1,), dtype)]) \
        if e.shape[0] == m - 1 else e.astype(dtype)
    _c = jnp.conj if conjugate else (lambda x: x)
    start2 = ee != 0                                # j starts a 2x2 block
    second2 = jnp.concatenate([jnp.zeros((1,), bool), start2[:-1]])
    # candidate 2x2 solutions for every j (used only where start2/second2)
    a = dd
    b = ee
    cdiag = jnp.concatenate([dd[1:], jnp.ones((1,), dtype)])
    det = a * cdiag - b * _c(b)
    det = jnp.where(det == 0, 1, det)
    y2 = jnp.concatenate([y[1:], jnp.zeros((1,) + y.shape[1:], dtype)])
    x_start = (cdiag[:, None] * y - _c(b)[:, None] * y2) / det[:, None]
    y1m = jnp.concatenate([jnp.zeros((1,) + y.shape[1:], dtype), y[:-1]])
    a_m = jnp.concatenate([jnp.ones((1,), dtype), a[:-1]])
    b_m = jnp.concatenate([jnp.ones((1,), dtype), b[:-1]])
    det_m = jnp.concatenate([jnp.ones((1,), dtype), det[:-1]])
    x_second = (a_m[:, None] * y - b_m[:, None] * y1m) / det_m[:, None]
    d_safe = jnp.where(dd == 0, 1, dd)
    x_single = y / d_safe[:, None]
    x = jnp.where(start2[:, None], x_start,
                  jnp.where(second2[:, None], x_second, x_single))
    return redistribute(Yvr.with_local(x), MC, MR)


def ldl_solve_after(Lp: DistMatrix, d, e, perm, B: DistMatrix,
                    conjugate: bool = True, nb: int | None = None,
                    precision=None) -> DistMatrix:
    """X = A^{-1} B from an ``ldl`` factorization (``ldl::SolveAfter``):
    P^T L D L^H P X = B."""
    orient = "C" if conjugate else "T"
    Bp = permute_rows(B, perm)
    Y = trsm("L", "L", "N", Lp, Bp, unit=True, nb=nb, precision=_hi(precision))
    Z = _block_diag_solve(d, e, Y, conjugate)
    X = trsm("L", "L", orient, Lp, Z, unit=True, nb=nb, precision=_hi(precision))
    return permute_rows(X, perm, inverse=True)


def symmetric_solve(A: DistMatrix, B: DistMatrix, uplo: str = "L",
                    nb: int | None = None, precision=None) -> DistMatrix:
    """Solve A X = B for symmetric A via pivoted LDL^T
    (``El::SymmetricSolve``)."""
    Lp, d, e, perm = ldl(A, uplo, conjugate=False, nb=nb, precision=_hi(precision))
    return ldl_solve_after(Lp, d, e, perm, B, conjugate=False, nb=nb,
                           precision=_hi(precision))


def hermitian_solve(A: DistMatrix, B: DistMatrix, uplo: str = "L",
                    nb: int | None = None, precision=None) -> DistMatrix:
    """Solve A X = B for Hermitian A via pivoted LDL^H
    (``El::HermitianSolve``)."""
    Lp, d, e, perm = ldl(A, uplo, conjugate=True, nb=nb, precision=_hi(precision))
    return ldl_solve_after(Lp, d, e, perm, B, conjugate=True, nb=nb,
                           precision=_hi(precision))


def inertia(d, e):
    """(num positive, num negative, num zero) eigenvalue counts from the
    Bunch-Kaufman D (``El::Inertia``; Sylvester's law of inertia).

    Each 2x2 block contributes one positive and one negative eigenvalue
    (Bunch-Kaufman 2x2 pivots are always indefinite)."""
    import numpy as np
    dn = np.asarray(d)
    en = np.asarray(e)
    m = dn.shape[0]
    en = np.concatenate([en, np.zeros(1, en.dtype)]) if en.shape[0] == m - 1 \
        else en
    start2 = en != 0
    second2 = np.concatenate([[False], start2[:-1]])
    single = ~(start2 | second2)
    npos = int(np.sum(np.real(dn[single]) > 0)) + int(np.sum(start2))
    nneg = int(np.sum(np.real(dn[single]) < 0)) + int(np.sum(start2))
    nzero = int(np.sum(np.real(dn[single]) == 0))
    return npos, nneg, nzero
