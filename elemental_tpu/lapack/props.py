"""Matrix properties: determinant, condition, inertia, norm estimates.

Reference: Elemental ``src/lapack_like/props/**`` -- ``Determinant.cpp``
(``El::Determinant``, ``SafeDeterminant`` via LU with pivot-sign),
``Condition.cpp`` (one/two/frobenius/infinity), ``Inertia.cpp`` (via
pivoted LDL), ``TwoNormEstimate.cpp`` (power iteration), ``Norm``
implementations (level-1 storage reductions live in
:mod:`..blas.level1`; the Schatten family is added here via the SVD).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.distmatrix import DistMatrix
from ..core.dist import MC, MR
from ..blas.level1 import (frobenius_norm, one_norm, infinity_norm,
                           get_diagonal)
from ..blas.level2 import gemv
from ..blas.level3 import _check_mcmr
from .lu import lu
from .cholesky import cholesky
from .ldl import ldl, inertia as _ldl_inertia
from .funcs import inverse


def _perm_sign(perm) -> float:
    """Parity of a permutation vector (host-side cycle count)."""
    p = np.asarray(perm)
    n = p.shape[0]
    seen = np.zeros(n, bool)
    sign = 1.0
    for i in range(n):
        if seen[i]:
            continue
        j = i
        clen = 0
        while not seen[j]:
            seen[j] = True
            j = int(p[j])
            clen += 1
        if clen % 2 == 0:
            sign = -sign
    return sign


def determinant(A: DistMatrix, nb: int | None = None, precision=None):
    """det(A) via LU with partial pivoting (``El::Determinant``)."""
    _check_mcmr(A)
    n = A.gshape[0]
    if A.gshape != (n, n):
        raise ValueError(f"determinant needs square, got {A.gshape}")
    if n == 0:
        return jnp.ones((), A.dtype)
    LU_, perm = lu(A, nb=nb, precision=precision)
    diag = get_diagonal(LU_).local[:, 0]
    return jnp.prod(diag) * _perm_sign(perm)


def safe_determinant(A: DistMatrix, nb: int | None = None, precision=None):
    """(rho, kappa, n) with det = rho * exp(kappa * n): unit-modulus rho and
    a log-scaled magnitude (``El::SafeDeterminant`` -- overflow-proof)."""
    _check_mcmr(A)
    n = A.gshape[0]
    if A.gshape != (n, n):
        raise ValueError(f"safe_determinant needs square, got {A.gshape}")
    if n == 0:
        return jnp.ones((), A.dtype), jnp.zeros(()), 0
    LU_, perm = lu(A, nb=nb, precision=precision)
    diag = get_diagonal(LU_).local[:, 0]
    mags = jnp.abs(diag)
    safe = jnp.where(mags == 0, 1.0, mags)
    rho = jnp.prod(jnp.where(mags == 0, 0.0, diag / safe)) * _perm_sign(perm)
    kappa = jnp.sum(jnp.log(safe)) / n
    kappa = jnp.where(jnp.any(mags == 0), -jnp.inf, kappa)
    return rho, kappa, n


def hpd_determinant(A: DistMatrix, uplo: str = "L", nb: int | None = None,
                    precision=None):
    """det of an HPD matrix via Cholesky: prod(diag(L))^2
    (``El::HPDDeterminant``)."""
    L = cholesky(A, uplo, nb=nb, precision=precision)
    diag = jnp.real(get_diagonal(L).local[:, 0])
    return jnp.prod(diag) ** 2


def two_norm_estimate(A: DistMatrix, iters: int = 20, seed: int = 0,
                      precision=None):
    """Power-iteration estimate of ||A||_2 (``El::TwoNormEstimate``)."""
    _check_mcmr(A)
    m, n = A.gshape
    from ..core.distmatrix import from_global
    rng = np.random.default_rng(seed)
    x = from_global(rng.normal(size=(n, 1)).astype(np.dtype(A.dtype))
                    if not jnp.issubdtype(A.dtype, jnp.complexfloating)
                    else (rng.normal(size=(n, 1))
                          + 1j * rng.normal(size=(n, 1))).astype(
                              np.dtype(A.dtype)),
                    MC, MR, grid=A.grid)
    nx0 = frobenius_norm(x)
    x = x.with_local(x.local / jnp.maximum(nx0, 1e-300))
    est = jnp.zeros((), jnp.zeros((), A.dtype).real.dtype)
    for _ in range(iters):
        # one step of power iteration on A^H A: est -> sigma_max^2
        y = gemv(A, x, precision=precision)
        z = gemv(A, y, orient="C", precision=precision)
        est = frobenius_norm(z)
        x = z.with_local(z.local / jnp.maximum(est, 1e-300))
    return jnp.sqrt(est)


def condition(A: DistMatrix, p: str = "two", nb: int | None = None,
              precision=None):
    """Condition number in the given norm (``El::Condition``)."""
    _check_mcmr(A)
    p = p.lower()
    if p in ("two", "2"):
        from .spectral import svd
        s = svd(A, vectors=False, nb=nb, precision=precision)
        smin = s[-1]
        return jnp.where(smin > 0, s[0] / jnp.where(smin == 0, 1, smin),
                         jnp.inf)
    Ai = inverse(A, nb=nb, precision=precision)
    if p in ("one", "1"):
        return one_norm(A) * one_norm(Ai)
    if p in ("inf", "infinity"):
        return infinity_norm(A) * infinity_norm(Ai)
    if p in ("frob", "frobenius"):
        return frobenius_norm(A) * frobenius_norm(Ai)
    raise ValueError(f"unknown norm {p!r}")


def inertia(A: DistMatrix, uplo: str = "L", nb: int | None = None,
            precision=None):
    """(n+, n-, n0) eigenvalue-sign counts of a Hermitian matrix via pivoted
    LDL + Sylvester's law (``El::Inertia``)."""
    _, d, e, _ = ldl(A, uplo, nb=nb, precision=precision)
    return _ldl_inertia(d, e)


def nuclear_norm(A: DistMatrix, nb: int | None = None, precision=None):
    """Sum of singular values (``El::NuclearNorm``)."""
    from .spectral import svd
    s = svd(A, vectors=False, nb=nb, precision=precision)
    return jnp.sum(s)


def schatten_norm(A: DistMatrix, p: float, nb: int | None = None,
                  precision=None):
    """(sum s_i^p)^(1/p) (``El::SchattenNorm``)."""
    from .spectral import svd
    s = svd(A, vectors=False, nb=nb, precision=precision)
    return jnp.sum(s ** p) ** (1.0 / p)


def two_norm(A: DistMatrix, nb: int | None = None, precision=None):
    """Largest singular value (``El::TwoNorm``)."""
    from .spectral import svd
    s = svd(A, vectors=False, nb=nb, precision=precision)
    return s[0]